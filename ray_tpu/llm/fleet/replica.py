"""Decode replica: one continuous-batching engine + prefix cache + driver.

One fleet member.  Each replica owns its own
:class:`~ray_tpu.llm.engine.InferenceEngine` (its own paged KV pool and
decode batch), a byte-bounded :class:`~ray_tpu.llm.fleet.prefix.
PrefixCache` of recently imported full-prompt handoffs, and a drive
thread that steps the engine and reports finishes through a callback —
the fleet server never steps engines itself, so N replicas decode
concurrently and a wedged replica stalls only its own stream.

Lifecycle is three states the router reads on every retry iteration:

``active``    accepting new imports
``draining``  finish in-flight work, admit nothing (scale-down, node
              drain — PR 7's evacuation protocol lands here)
``dead``      drive thread stopped; the fleet sheds whatever was mapped
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..._private import sanitizer
from ..engine import InferenceEngine, SamplingParams
from .prefix import PrefixCache

STATE_ACTIVE = "active"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"


class DecodeReplica:
    """One decode engine behind the fleet router."""

    def __init__(self, build_params, *, name: str,
                 engine_options: Optional[Dict[str, Any]] = None,
                 cache_capacity_bytes: int = 64 * 1024 * 1024,
                 record_token_times: bool = False,
                 on_finish: Optional[Callable[["DecodeReplica", Any],
                                              None]] = None,
                 poll_interval_s: float = 0.002):
        params, cfg = build_params() if callable(build_params) \
            else build_params
        eo = dict(engine_options or {})
        # Replicas are decode-only: prefill happens on the prefill tier
        # and arrives as a handoff, never through the chunked path.
        eo.pop("prefill_chunk", None)
        self.name = name
        self.engine = InferenceEngine(
            params, cfg, record_token_times=record_token_times, **eo)
        self.cache = PrefixCache(
            capacity_bytes=cache_capacity_bytes,
            block=eo.get("page_size", 16))
        self.state = STATE_ACTIVE
        self._on_finish = on_finish
        self._stop = threading.Event()
        self._work = threading.Event()
        self._poll = poll_interval_s
        self._driver = sanitizer.spawn(
            self._drive_loop, name=f"fleet-decode-{name}")

    # -- intake -------------------------------------------------------------

    @property
    def accepting(self) -> bool:
        return self.state == STATE_ACTIVE

    def import_prefill(self, handoff, retain: bool = True
                       ) -> Optional[int]:
        """Join a prefilled request to this replica's batch.  None means
        backpressure OR not accepting — the dispatcher checks ``state``
        between retries and re-routes instead of spinning on a draining
        replica.  ``retain=True`` keeps a host copy of the handoff in
        the prefix cache (greedy handoffs only: a cached first token is
        replayable only when it was the argmax)."""
        if not self.accepting:
            return None
        rid = self.engine.import_prefill(handoff)
        if rid is not None:
            if retain and handoff.params.temperature <= 0.0:
                self.cache.insert(_host_copy(handoff))
            self._work.set()
        return rid

    def try_serve_cached(self, prompt_tokens: Sequence[int],
                         params: SamplingParams,
                         t_submit: float = 0.0) -> Optional[int]:
        """Full prefix hit: replay the cached handoff straight into the
        decode batch, skipping the prefill tier.  Greedy requests only
        (the cached first token is the argmax; any temperature would
        need a fresh sample from logits the cache doesn't keep).
        Returns the engine rid, or None (miss / non-greedy / engine
        backpressure — caller falls back to the cold path)."""
        if not self.accepting or params.temperature > 0.0:
            return None
        cached = self.cache.lookup(prompt_tokens)
        if cached is None:
            return None
        now = time.perf_counter()
        # The request's own sampling envelope rides the replay:
        # import_prefill reads max_tokens/stop ids from handoff.params.
        replay = dataclasses.replace(
            cached, params=params, t_submit=t_submit or now, t_first=now)
        rid = self.engine.import_prefill(replay)
        if rid is not None:
            self._work.set()
        return rid

    def cancel(self, rid: int) -> None:
        self.engine.cancel(rid)

    # -- drive --------------------------------------------------------------

    def _drive_loop(self) -> None:
        while not self._stop.is_set():
            if not self.engine.has_work():
                self._work.wait(0.02)
                self._work.clear()
                continue
            for req in self.engine.step():
                if self._on_finish is not None:
                    self._on_finish(self, req)

    # -- introspection ------------------------------------------------------

    def load_stats(self) -> Dict[str, Any]:
        """Router-facing load: engine occupancy/queues + cache stats."""
        stats = self.engine.load_stats()
        stats["name"] = self.name
        stats["state"] = self.state
        stats["ongoing"] = len(self.engine.running)
        stats["cache"] = self.cache.stats()
        return stats

    def summary(self) -> Dict[str, Any]:
        """Prefix-index digest for affinity scoring."""
        return self.cache.summary()

    def idle(self) -> bool:
        return not self.engine.has_work() and not self.engine.running

    # -- lifecycle ----------------------------------------------------------

    def drain(self) -> None:
        """Stop admitting; in-flight work runs to completion.  The fleet
        manager polls :meth:`idle` and then :meth:`kill`s."""
        if self.state == STATE_ACTIVE:
            self.state = STATE_DRAINING

    def kill(self, timeout_s: float = 5.0) -> List[int]:
        """Hard stop (chaos / scale-down tail): stop the drive thread
        and return the engine rids that were still in flight — the
        fleet sheds exactly those, retriably."""
        self.state = STATE_DEAD
        self._stop.set()
        self._work.set()
        self._driver.join(timeout_s)
        with self.engine._lock:
            lost = list(self.engine.running)
        return lost

    close = kill


def _host_copy(handoff):
    """Own-memory copy of a handoff for cache retention: the imported
    arrays may be views into a shm mapping whose keepalive dies when
    the dispatcher returns."""
    return dataclasses.replace(
        handoff,
        prompt_tokens=list(handoff.prompt_tokens),
        ks=np.ascontiguousarray(handoff.ks),
        vs=np.ascontiguousarray(handoff.vs))
