"""Adaptive search algorithms: Searcher ABC, TPE, limiter/repeater wrappers.

Reference analog: python/ray/tune/search/ — Searcher (searcher.py),
ConcurrencyLimiter (search_generator/concurrency limiting), Repeater
(repeater.py), and the external-library searchers (optuna/hyperopt/bohb).
The external deps aren't available here, so the model-based searcher is a
self-contained pure-numpy TPE (Bergstra et al. 2011, the algorithm behind
hyperopt/optuna defaults): split observations into good/bad quantiles,
model each with a kernel density, and suggest the candidate maximizing the
good/bad density ratio.  Combine ``TPESearcher`` with the HyperBand
scheduler for BOHB-style behavior (model-based sampling + bracketed early
stopping).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .search import Choice, GridSearch, LogUniform, RandInt, Uniform


class Searcher:
    """Suggest/observe interface (reference: tune/search/searcher.py).

    ``suggest(trial_id)`` returns a config dict (or None when the searcher
    has nothing to offer right now); ``on_trial_complete(trial_id, score)``
    feeds the final metric back.  ``mode`` normalization (min/max) is the
    Tuner's job: searchers always MINIMIZE the reported score.
    """

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          score: Optional[float]) -> None:
        pass


class BasicVariantSearcher(Searcher):
    """Random/grid sampling as a Searcher (reference:
    BasicVariantGenerator)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: int = 0):
        from .search import generate_variants
        self._variants = generate_variants(param_space, num_samples, seed)
        self._next = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over the tune search space.

    Supports Uniform / LogUniform / RandInt / Choice dimensions (grid axes
    are static by nature — use BasicVariantSearcher for those).  Constants
    pass through unchanged.
    """

    def __init__(self, param_space: Dict[str, Any], *,
                 n_startup_trials: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, epsilon: float = 0.15,
                 seed: int = 0):
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    f"TPESearcher does not support grid_search ({k!r}); "
                    "use BasicVariantSearcher or expand the grid manually")
        self.space = param_space
        self.n_startup = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        # Fraction of model-phase suggestions drawn uniformly at random:
        # the density-ratio argmax alone cannot leave an established
        # cluster (distant candidates always lose on g-density), so a
        # random restart share is what finds better basins.
        self.epsilon = epsilon
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Tuple[Dict[str, Any], float]] = []

    # -- space helpers ------------------------------------------------------

    def _sample_random(self) -> Dict[str, Any]:
        cfg = {}
        for k, v in self.space.items():
            if isinstance(v, (Choice, Uniform, LogUniform, RandInt)):
                cfg[k] = v.sample(self._rng)
            else:
                cfg[k] = v
        return cfg

    @staticmethod
    def _to_unit(dim, value) -> Optional[float]:
        """Map a numeric dimension's value into continuous model space."""
        if isinstance(dim, Uniform):
            return float(value)
        if isinstance(dim, LogUniform):
            return math.log(float(value))
        if isinstance(dim, RandInt):
            return float(value)
        return None

    @staticmethod
    def _from_unit(dim, x: float):
        if isinstance(dim, Uniform):
            return float(np.clip(x, dim.low, dim.high))
        if isinstance(dim, LogUniform):
            return float(np.clip(math.exp(x), dim.low, dim.high))
        if isinstance(dim, RandInt):
            return int(np.clip(round(x), dim.low, dim.high - 1))
        return x

    # -- TPE core ------------------------------------------------------------

    @staticmethod
    def _adaptive_bw(samples: np.ndarray, span: float) -> np.ndarray:
        """Per-kernel bandwidths from neighbor gaps (the adaptive-Parzen
        heuristic hyperopt uses): isolated points get wide kernels that
        spread mass across unexplored territory; clustered points get
        narrow ones.  Clipped to [2%, 100%] of the dimension span."""
        n = len(samples)
        if n == 1:
            return np.array([span / 2.0])
        order = np.argsort(samples)
        s = samples[order]
        gaps = np.empty(n)
        gaps[0] = s[1] - s[0]
        gaps[-1] = s[-1] - s[-2]
        if n > 2:
            gaps[1:-1] = np.maximum(s[2:] - s[1:-1], s[1:-1] - s[:-2])
        bw_sorted = np.clip(gaps, span * 0.02, span)
        bw = np.empty(n)
        bw[order] = bw_sorted
        return bw

    @staticmethod
    def _kde_logpdf(samples: np.ndarray, bw: np.ndarray,
                    xs: np.ndarray) -> np.ndarray:
        """Mixture-of-Gaussians log-density with per-kernel bandwidths."""
        d = (xs[:, None] - samples[None, :]) / bw[None, :]
        logk = -0.5 * d * d - np.log(bw[None, :] *
                                     math.sqrt(2 * math.pi))
        m = logk.max(axis=1)
        return m + np.log(np.exp(logk - m[:, None]).sum(axis=1) + 1e-300) \
            - math.log(len(samples))

    def _suggest_model(self) -> Dict[str, Any]:
        scores = np.array([s for _, s in self._obs])
        order = np.argsort(scores)  # minimize
        n_good = max(1, int(math.ceil(self.gamma * len(self._obs))))
        good_idx = set(order[:n_good].tolist())
        good = [self._obs[i][0] for i in range(len(self._obs))
                if i in good_idx]
        bad = [self._obs[i][0] for i in range(len(self._obs))
               if i not in good_idx] or good
        cfg: Dict[str, Any] = {}
        for k, dim in self.space.items():
            if isinstance(dim, Choice):
                # Category ratio with +1 smoothing.
                counts_g = {v: 1.0 for v in range(len(dim.values))}
                counts_b = {v: 1.0 for v in range(len(dim.values))}
                for c in good:
                    counts_g[dim.values.index(c[k])] += 1
                for c in bad:
                    counts_b[dim.values.index(c[k])] += 1
                ratio = {i: counts_g[i] / counts_b[i]
                         for i in range(len(dim.values))}
                best = max(ratio, key=ratio.get)
                cfg[k] = dim.values[best]
            elif isinstance(dim, (Uniform, LogUniform, RandInt)):
                g = np.array([self._to_unit(dim, c[k]) for c in good])
                b = np.array([self._to_unit(dim, c[k]) for c in bad])
                if isinstance(dim, LogUniform):
                    lo, hi = math.log(dim.low), math.log(dim.high)
                else:
                    lo, hi = float(dim.low), float(dim.high)
                span = hi - lo
                g_bw = self._adaptive_bw(g, span)
                b_bw = self._adaptive_bw(b, span)
                # Candidates: kernel draws from the good KDE plus a
                # uniform-prior share (hyperopt mixes a uniform prior into
                # l(x) so unexplored territory keeps nonzero density).
                n_kde = max(1, (3 * self.n_candidates) // 4)
                n_uni = self.n_candidates - n_kde
                picks = self._np_rng.choice(len(g), n_kde)
                cand = np.concatenate([
                    g[picks] + self._np_rng.normal(0, 1, n_kde) *
                    g_bw[picks],
                    self._np_rng.uniform(lo, hi, n_uni)])
                cand = np.clip(cand, lo, hi)
                # Uniform-prior mixing (weight ~1 virtual point) keeps the
                # ratio finite far from both sets.
                prior = -math.log(span)
                lg = np.logaddexp(self._kde_logpdf(g, g_bw, cand),
                                  prior - math.log(len(g) + 1))
                lb = np.logaddexp(self._kde_logpdf(b, b_bw, cand),
                                  prior - math.log(len(b) + 1))
                cfg[k] = self._from_unit(dim, float(cand[np.argmax(lg - lb)]))
            else:
                cfg[k] = dim
        return cfg

    # -- Searcher interface ---------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._obs) < self.n_startup or \
                self._rng.random() < self.epsilon:
            cfg = self._sample_random()
        else:
            cfg = self._suggest_model()
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          score: Optional[float]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is not None and score is not None and math.isfinite(score):
            self._obs.append((cfg, float(score)))


class ConcurrencyLimiter(Searcher):
    """Cap outstanding suggestions (reference:
    tune/search/concurrency_limiter.py) — essential for model-based
    searchers whose quality depends on completed observations."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id: str,
                          score: Optional[float]) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, score)


class Repeater(Searcher):
    """Repeat each underlying suggestion N times and report the mean back
    (reference: tune/search/repeater.py — noise-robust evaluation)."""

    def __init__(self, searcher: Searcher, repeat: int):
        if repeat < 1:
            raise ValueError("repeat must be >= 1")
        self.searcher = searcher
        self.repeat = repeat
        self._groups: Dict[str, Dict[str, Any]] = {}
        self._trial_group: Dict[str, str] = {}
        self._counter = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        # Find a group that still needs repeats.
        for gid, g in self._groups.items():
            if g["launched"] < self.repeat:
                g["launched"] += 1
                self._trial_group[trial_id] = gid
                return dict(g["config"])
        gid = f"group-{self._counter}"
        self._counter += 1
        cfg = self.searcher.suggest(gid)
        if cfg is None:
            return None
        self._groups[gid] = {"config": cfg, "launched": 1, "completed": 0,
                             "scores": []}
        self._trial_group[trial_id] = gid
        return dict(cfg)

    def on_trial_complete(self, trial_id: str,
                          score: Optional[float]) -> None:
        gid = self._trial_group.pop(trial_id, None)
        if gid is None:
            return
        g = self._groups[gid]
        g["completed"] += 1
        if score is not None:
            g["scores"].append(score)
        if g["completed"] >= self.repeat:
            mean = float(np.mean(g["scores"])) if g["scores"] else None
            self.searcher.on_trial_complete(gid, mean)
            del self._groups[gid]
