"""Worker process: task execution loop.

The reference's worker is a language process embedding the C++ CoreWorker
(reference: src/ray/core_worker/core_worker.h:167) — a gRPC server receiving
PushTask, a TaskReceiver with per-concurrency-group thread/fiber pools
(reference: task_execution/task_receiver.h:43), and client stubs for
submitting nested work.  Here the worker is a spawned Python process with a
receiver thread (the transport endpoint), an executor pool (the concurrency
groups), and a ``WorkerRuntime`` that the public API routes through when
called from inside a task — so nested ``.remote()`` / ``get`` / ``put`` work
exactly as on the driver (reference: core_worker.h SubmitTask/Get/Put).
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple

from . import serialization, wire
from .config import Config
from .exceptions import TaskError
from .ids import ActorID, ObjectID, TaskID, WorkerID
from .object_store import ArenaReader, RemoteObjectReader
from .protocol import (ActorStateMsg, AllocReply, AllocRequest,
                       BorrowRetained, GetReply, GetRequest, KillWorker,
                       ProfileReply, ProfileRequest, PutFromWorker,
                       ReadDone, RpcCall, RpcReply, RunTask, SealObject,
                       StackDumpReply, StackDumpRequest, SubmitFromWorker,
                       TaskDone, WaitReply, WaitRequest, WorkerReady)


def _materialize(desc, keepalives: List, rt=None) -> Any:
    kind = desc[0]
    if kind == "inline":
        return serialization.unpack_payload(desc[1])
    if kind == "shm":
        value, shm = RemoteObjectReader.read(desc[1], desc[2])
        keepalives.append(shm)
        return value
    if kind == "shma":
        value, shm = ArenaReader.read(desc)
        keepalives.append(shm)
        return value
    if kind == "err":
        raise serialization.unpack_payload(desc[1])
    if kind == "ref" and rt is not None:
        # Unresolved dependency (direct worker->worker call frames carry
        # raw refs; the callee resolves): blocks until the value lands.
        return rt.get([ObjectID(desc[1])])[0]
    raise ValueError(f"unknown value descriptor {kind!r}")


def _serialize_result(rt: "WorkerRuntime", object_id: ObjectID, value: Any):
    meta, buffers = serialization.serialize_payload(value)
    nbytes = serialization.payload_nbytes(meta, buffers)
    if nbytes <= Config.get("max_inline_object_size"):
        out = bytearray(nbytes)
        serialization.write_payload_into(memoryview(out), meta, buffers)
        return ("inline", bytes(out))
    # Preferred path: zero-copy write into the node's C++ arena store
    # (plasma Create/Seal protocol). Fallback: dedicated shm segment.
    if rt.arena_segment:
        grant = rt.alloc_arena(object_id, nbytes)
        if grant is not None:
            seg, off = grant
            ArenaReader.write(seg, off, meta, buffers)
            rt.send(SealObject(object_id))
            return ("shma", seg, off, nbytes, object_id.binary())
    shm_name, nbytes = RemoteObjectReader.write_payload(object_id, meta,
                                                        buffers)
    return ("shm", shm_name, nbytes)


class WorkerRuntime:
    """Runtime facade available inside a worker process.

    Implements the same surface the driver Runtime exposes to the public API
    (submit/get/put/wait/kv/actor lookup), forwarding over the worker pipe.
    """

    def __init__(self, conn, worker_id: WorkerID, job_id):
        self.conn = conn
        self.worker_id = worker_id
        self.job_id = job_id
        self._send_lock = threading.Lock()
        # Outgoing messages coalesce through a sender thread (mirror of the
        # node's _send_loop): everything queued since the last write goes
        # out as one list frame.  FIFO preserves Seal-before-TaskDone and
        # alive-before-results ordering.
        import collections
        self._outbox: Any = collections.deque()
        self._out_ev = threading.Event()
        self._send_closed = False
        self._sender = threading.Thread(target=self._send_loop,
                                        name="worker-sender", daemon=True)
        self._sender.start()
        self._req_lock = threading.Lock()
        self._next_req = 0
        self._pending: Dict[int, queue.Queue] = {}
        self.current_task_id: Optional[TaskID] = None
        self.current_actor_id: Optional[ActorID] = None
        # thread ident -> (task_id_hex, task_name) while that thread runs a
        # task: lets a StackDumpRequest name what each thread executes
        # (concurrent actor methods make the single current_task_id racy).
        self.thread_tasks: Dict[int, Tuple[str, str]] = {}
        self._obj_index_lock = threading.Lock()
        self._obj_index = 1 << 20  # put-objects live above return indices
        self.arena_segment = os.environ.get("RAY_TPU_ARENA_SEG") or None
        # Per-task deferred pin releases for GetReply descriptors: released
        # when the task that materialized them finishes (its zero-copy views
        # die with it). Thread-local so concurrent tasks don't cross-release.
        self._tls = threading.local()
        # -- direct worker->worker actor calls (see direct.py) ------------ #
        # Caller-owned results of direct calls live here (oid bytes ->
        # _LocalObject); the head only learns about them on escape.
        tok = os.environ.get("RAY_TPU_DIRECT_TOKEN")
        self.direct_token = bytes.fromhex(tok) if tok else None
        self._local_lock = threading.Lock()
        self._local_objects: Dict[bytes, Any] = {}
        self._channels: Dict[bytes, Any] = {}   # actor_id bytes -> channel
        self._direct_mode: Dict[bytes, str] = {}  # "direct" | "classic"

    # -- direct-call plumbing (caller side) -------------------------------- #

    def local_ready(self, oid_bytes: bytes, desc) -> None:
        with self._local_lock:
            lo = self._local_objects.get(oid_bytes)
            if lo is None:
                return
            promote = lo.promote_on_ready and desc[0] in ("inline", "err")
            lo.set(desc)
            lo.promote_on_ready = False
            if lo.refcount <= 0 and lo.ref_seen and not promote:
                # Fire-and-forget call whose ref was created AND dropped:
                # nothing will ever read this result — don't accumulate
                # it.  ref_seen guards the submit window where the reply
                # can land before the caller has built its ObjectRef.
                self._local_objects.pop(oid_bytes, None)
        if promote:
            self.send(PutFromWorker(ObjectID(oid_bytes), desc))

    def promote_local(self, object_id) -> None:
        """A direct-call result ref escapes this process (pickled into a
        task arg / user payload): register it with the head so classic
        resolution works anywhere (reference: borrow registration,
        reference_counter.h:44).  Pending results promote on arrival."""
        ob = object_id.binary() if not isinstance(object_id, bytes) \
            else object_id
        with self._local_lock:
            lo = self._local_objects.get(ob)
            if lo is None:
                return
            if not lo.event.is_set():
                lo.promote_on_ready = True
                return
        if lo.desc[0] in ("inline", "err"):
            self.send(PutFromWorker(ObjectID(ob), lo.desc))

    def drop_local(self, oid_bytes: bytes) -> None:
        with self._local_lock:
            lo = self._local_objects.get(oid_bytes)
            if lo is None:
                return
            lo.refcount -= 1
            if lo.refcount <= 0 and lo.event.is_set() \
                    and not lo.promote_on_ready:
                # Pending entries (event unset) are cleaned by
                # local_ready when the reply lands and refcount is 0.
                self._local_objects.pop(oid_bytes, None)

    def note_local_ref(self, oid_bytes: bytes) -> None:
        with self._local_lock:
            lo = self._local_objects.get(oid_bytes)
            if lo is not None:
                lo.refcount += 1
                lo.ref_seen = True

    def note_new_ref(self, ref) -> None:
        """Every ObjectRef constructed in this worker passes through here:
        local-table refcounting plus borrow tracking while task args are
        being materialized (reference: reference_counter.h:44 borrower
        registration on deserialization)."""
        self.note_local_ref(ref._id.binary())
        borrows = getattr(self._tls, "arg_borrows", None)
        if borrows is not None:
            import weakref
            try:
                borrows.append((weakref.ref(ref), ref._id))
            except TypeError:
                pass

    def begin_arg_borrows(self) -> None:
        self._tls.arg_borrows = []

    def end_arg_borrows(self) -> list:
        borrows = getattr(self._tls, "arg_borrows", None)
        self._tls.arg_borrows = None
        return borrows or []

    def report_retained_borrows(self, borrows: list) -> None:
        """After the task: any arg-borrowed ref still alive (stored in
        actor state, a module global, ...) escalates to owner-side
        escaped pinning — the bounded fallback."""
        survivors = [oid for (wref, oid) in borrows
                     if wref() is not None]
        if survivors:
            self.send(BorrowRetained(survivors))

    def submit_actor_direct(self, actor_id, task_id, name: str,
                            method_name: Optional[str], return_ids: List,
                            args: list, kwargs: dict,
                            max_concurrency: int, streaming: bool,
                            fn_blob: Optional[bytes] = None) -> bool:
        """Push an actor call straight to the actor's worker over this
        process's channel.  Mode (direct vs classic) is sticky per actor
        so the two paths never interleave for one caller (ordering)."""
        if self.direct_token is None:
            return False
        ab = actor_id.binary()
        mode = self._direct_mode.get(ab)
        if mode is None:
            try:
                res = self.control("resolve_actor_direct", ab)
            except Exception:
                res = None
            state = res[0] if res else "unknown"
            if state == "alive" and res[1] is not None:
                mode = "direct"
            else:
                # Classic is STICKY: once any call from this process rode
                # the head's dispatch queue, later direct pushes could
                # overtake it on a separate socket and break per-caller
                # ordering — so this caller stays classic for this actor.
                mode = "classic"
            self._direct_mode[ab] = mode
        if mode != "direct":
            return False
        from .direct import DirectChannel
        ch = self._channels.get(ab)
        if ch is None:
            ch = self._channels.setdefault(
                ab, DirectChannel(self, actor_id))
            with ch.lock:
                ch._ensure_resolver_locked()
        tb = task_id.binary()
        if not streaming:
            with self._local_lock:
                from .direct import _LocalObject
                for oid in return_ids:
                    self._local_objects[oid.binary()] = _LocalObject()
        frame = (wire.RUN_TASK, tb, name, fn_blob, None, method_name,
                 tuple(r.binary() for r in return_ids), ab,
                 streaming, max_concurrency, None, args, kwargs, None)
        ch.submit(frame, return_ids)
        return True

    # -- plumbing -----------------------------------------------------------

    def send(self, msg) -> None:
        self._outbox.append(msg)
        self._out_ev.set()

    def _send_loop(self) -> None:
        outbox, ev = self._outbox, self._out_ev
        while True:
            ev.wait()
            ev.clear()
            batch: List = []
            while True:
                try:
                    batch.append(outbox.popleft())
                except IndexError:
                    break
            if batch:
                try:
                    with self._send_lock:
                        self.conn.send(batch if len(batch) > 1 else batch[0])
                except (BrokenPipeError, OSError):
                    return  # node gone; recv loop exits the process
                except Exception:
                    # Unpicklable message: send individually so one bad
                    # frame can't kill the sender (which would silently
                    # wedge every future TaskDone/reply).
                    for m in batch:
                        try:
                            with self._send_lock:
                                self.conn.send(m)
                        except (BrokenPipeError, OSError):
                            return
                        except Exception:
                            traceback.print_exc()
            if self._send_closed and not outbox:
                return

    def flush_and_close(self, timeout: float = 2.0) -> None:
        """Drain queued messages (the final TaskDone must hit the wire
        before os._exit)."""
        self._send_closed = True
        self._out_ev.set()
        self._sender.join(timeout=timeout)

    def _call(self, make_msg, timeout: Optional[float] = None):
        with self._req_lock:
            self._next_req += 1
            rid = self._next_req
            q: queue.Queue = queue.Queue()
            self._pending[rid] = q
        self.send(make_msg(rid))
        try:
            return q.get(timeout=timeout)
        finally:
            with self._req_lock:
                self._pending.pop(rid, None)

    def deliver_reply(self, request_id: int, reply) -> None:
        with self._req_lock:
            q = self._pending.get(request_id)
        if q is not None:
            q.put(reply)

    # -- API surface --------------------------------------------------------

    def submit_spec(self, spec) -> None:
        # Caller-local direct-call results used as args must be
        # registered with the head before it resolves this spec's deps.
        for kind, p in list(spec.arg_descs) + list(spec.kwarg_descs.values()):
            if kind == "ref":
                self.promote_local(p)
        self.send(SubmitFromWorker(spec))

    def get(self, object_ids: List[ObjectID], timeout: Optional[float] = None):
        # Safe bare read: empty-dict fast path; _split_local takes the
        # lock before touching individual entries.
        if self._local_objects:  # ray-tpu: noqa[RT401]
            local = self._split_local(object_ids, timeout)
            if local is not None:
                return local
        reply: GetReply = self._call(
            lambda rid: GetRequest(rid, self.worker_id, object_ids, timeout),
            timeout=None)
        has_arena = any(isinstance(d, tuple) and d and d[0] == "shma"
                        for d in reply.values)
        if reply.timed_out:
            # The node pinned the ready arena objects before replying; no
            # views were created, so release immediately.
            if has_arena:
                self._send_read_done(reply.request_id, retain=False)
            from .exceptions import GetTimeoutError
            raise GetTimeoutError(f"get timed out on {object_ids}")
        keepalives: List = []
        values = None
        try:
            values = [_materialize(d, keepalives) for d in reply.values]
            return values
        finally:
            if has_arena:
                arena_values = None
                if values is not None:
                    arena_values = [v for d, v in zip(reply.values, values)
                                    if isinstance(d, tuple) and d
                                    and d[0] == "shma"]
                self._note_arena_read(reply.request_id, arena_values)

    def _split_local(self, object_ids: List[ObjectID],
                     timeout: Optional[float] = None):
        """Resolve ids that are local direct-call results without a head
        round-trip; the rest go through the classic get.  Returns ordered
        values, or None when nothing is local."""
        with self._local_lock:
            entries = [self._local_objects.get(o.binary())
                       for o in object_ids]
        if not any(e is not None for e in entries):
            return None
        values: List[Any] = [None] * len(object_ids)
        classic_ids: List[ObjectID] = []
        classic_pos: List[int] = []
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        for i, (oid, lo) in enumerate(zip(object_ids, entries)):
            if lo is None:
                classic_ids.append(oid)
                classic_pos.append(i)
                continue
            remaining = None if deadline is None \
                else deadline - _time.monotonic()
            if not lo.event.wait(remaining):
                from .exceptions import GetTimeoutError
                raise GetTimeoutError(f"get timed out on {oid}")
            desc = lo.desc
            if desc[0] == "err":
                raise serialization.unpack_payload(desc[1])
            if desc[0] == "inline":
                values[i] = serialization.unpack_payload(desc[1])
            else:
                # Result registered upstream (non-inline): the head owns
                # it now — drop the local entry (else the classic get
                # below would re-enter this path forever) and resolve
                # through the head.
                with self._local_lock:
                    self._local_objects.pop(oid.binary(), None)
                classic_ids.append(oid)
                classic_pos.append(i)
        if classic_ids:
            remaining = None if deadline is None \
                else max(deadline - _time.monotonic(), 0.0)
            rest = self.get(classic_ids, remaining)
            for pos, v in zip(classic_pos, rest):
                values[pos] = v
        return values

    def _send_read_done(self, request_id: int, retain: bool) -> None:
        try:
            self.send(ReadDone(request_id, retain))
        except (BrokenPipeError, OSError):
            pass  # node gone; pins die with it

    def _note_arena_read(self, request_id: int, arena_values) -> None:
        """Schedule the pin release for a GetReply holding arena descriptors.

        Task context: released when the task ends (its views die with it).
        Actor context: the actor may retain zero-copy views in its state, so
        release when the *values* are garbage-collected (plasma buffer
        release semantics); values that can't carry a weakref fall back to
        worker-lifetime pins. No context / materialize error: release now.
        """
        if self.current_actor_id is None:
            deferred = getattr(self._tls, "read_dones", None)
            if deferred is not None:
                deferred.append(request_id)
            else:
                self._send_read_done(request_id, retain=False)
            return
        if not arena_values:
            self._send_read_done(request_id, retain=False)
            return
        import weakref
        remaining = {"n": len(arena_values)}
        rlock = threading.Lock()

        def one_collected():
            with rlock:
                remaining["n"] -= 1
                done = remaining["n"] == 0
            if done:
                self._send_read_done(request_id, retain=False)

        finalizers = []
        try:
            for v in arena_values:
                finalizers.append(weakref.finalize(v, one_collected))
        except TypeError:
            # Some value can't be weakly referenced: pin for the worker's
            # lifetime instead (node releases at worker death).
            for f in finalizers:
                f.detach()
            self._send_read_done(request_id, retain=True)

    def begin_task_reads(self) -> None:
        self._tls.read_dones = []

    def flush_task_reads(self) -> None:
        deferred = getattr(self._tls, "read_dones", None)
        self._tls.read_dones = None
        for rid in deferred or ():
            self.send(ReadDone(rid, retain=False))

    def alloc_arena(self, object_id: ObjectID, nbytes: int):
        reply: AllocReply = self._call(
            lambda rid: AllocRequest(rid, self.worker_id, object_id, nbytes))
        if reply.segment is None:
            return None
        return reply.segment, reply.offset

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        local_map = {}
        if self._local_objects:
            with self._local_lock:
                for o in object_ids:
                    lo = self._local_objects.get(o.binary())
                    if lo is not None:
                        local_map[o] = lo
        if not local_map:
            reply: WaitReply = self._call(
                lambda rid: WaitRequest(rid, self.worker_id, object_ids,
                                        num_returns, timeout, fetch_local))
            ready_set = set(reply.ready)
            ready = [o for o in object_ids if o in ready_set]
            not_ready = [o for o in object_ids if o not in ready_set]
            return ready, not_ready
        # Mixed local/classic: poll in slices — local results complete via
        # channel replies, the rest via short head waits.
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        classic = [o for o in object_ids if o not in local_map]
        while True:
            ready = []
            for o in object_ids:
                lo = local_map.get(o)
                if lo is not None and lo.event.is_set():
                    ready.append(o)
            classic_ready: set = set()
            if classic:
                # 0.5s slices bound the polling load on the head while
                # local channel replies keep landing concurrently.
                reply = self._call(
                    lambda rid: WaitRequest(
                        rid, self.worker_id, classic,
                        len(classic), 0.5, fetch_local))
                classic_ready = set(reply.ready)
                ready.extend(o for o in object_ids if o in classic_ready)
            if len(ready) >= num_returns or (
                    deadline is not None
                    and _time.monotonic() >= deadline):
                ready = ready[:max(num_returns, 0)] \
                    if len(ready) > num_returns else ready
                rset = set(ready)
                return ready, [o for o in object_ids if o not in rset]
            if not classic:
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                # Pure local: block on the first unready event in slices.
                pending = [lo for o, lo in local_map.items()
                           if not lo.event.is_set()]
                if pending:
                    pending[0].event.wait(
                        0.05 if remaining is None
                        else min(0.05, max(remaining, 0.0)))

    def put(self, value: Any) -> ObjectID:
        task_id = self.current_task_id or TaskID.for_driver(self.job_id)
        with self._obj_index_lock:
            self._obj_index += 1
            idx = self._obj_index
        object_id = ObjectID.of(task_id, idx)
        # Refs inside the value: containment-retained by the owner for
        # this object's lifetime (see _run_task's result handling).
        from .api import _nested_collector
        inner: list = []
        token = _nested_collector.set(inner)
        try:
            desc = _serialize_result(self, object_id, value)
        finally:
            _nested_collector.reset(token)
        if inner:
            from .protocol import ContainedRefs
            self.send(ContainedRefs(object_id, list(inner)))
        self.send(PutFromWorker(object_id, desc))
        return object_id

    def control(self, method: str, *args, **kwargs):
        """Generic control-plane call (KV, named actors, PGs, metadata)."""
        reply: RpcReply = self._call(
            lambda rid: RpcCall(rid, self.worker_id, method, args, kwargs))
        if reply.error is not None:
            raise RuntimeError(reply.error)
        return reply.value


class _TaskPool:
    """Minimal thread pool: SimpleQueue + persistent threads.  Replaces
    ThreadPoolExecutor on the task path — no Future allocation, no
    work-item wrapper, ~10us less per submit."""

    def __init__(self, size: int = 1):
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._size = 0
        self.resize(size)

    def resize(self, n: int) -> None:
        while self._size < n:
            self._size += 1
            from . import sanitizer
            sanitizer.spawn(self._loop, name="task-exec")

    @property
    def size(self) -> int:
        return self._size

    def submit(self, fn, arg) -> None:
        self._q.put((fn, arg))

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, arg = item
            try:
                fn(arg)
            except Exception:
                traceback.print_exc()

    def shutdown(self) -> None:
        for _ in range(self._size):
            self._q.put(None)


class WorkerLoop:
    def __init__(self, conn, worker_id: WorkerID, job_id):
        self.runtime = WorkerRuntime(conn, worker_id, job_id)
        # fn_id -> unpickled callable (reference: worker-side function
        # cache over the GCS function table) — repeated tasks on the same
        # function skip both the blob bytes on the wire and the unpickle.
        self._fn_cache: Dict[bytes, Any] = {}
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._executor = _TaskPool(1)
        self._actor_lock = threading.Lock()
        # With max_concurrency > 1 the executor pool may pick up method
        # tasks while __init__ is still running on another thread; methods
        # gate on this event (set when construction finishes or fails).
        self._actor_ready = threading.Event()
        # Shm segments backing zero-copy views that an actor may retain in
        # its state must outlive the task that mapped them.
        self._actor_keepalives: List = []
        self._direct_server: Any = None

    def _direct_addr(self) -> Optional[Tuple[str, int]]:
        """Start (once) and advertise this worker's direct-call listener —
        peers push actor calls straight here (direct.py)."""
        if self.runtime.direct_token is None:
            return None
        if self._direct_server is None:
            try:
                from .direct import DirectServer
                self._direct_server = DirectServer(
                    self, self.runtime.direct_token,
                    host=os.environ.get("RAY_TPU_DIRECT_HOST",
                                        "127.0.0.1"))
            except Exception:
                traceback.print_exc()
                return None
        return self._direct_server.address

    def _load_fn(self, spec) -> Any:
        """Resolve the task's callable: cached by fn_id, blob from the
        spec, or fetched from the driver's function table (stripped spec
        raced a lost first delivery)."""
        if spec.fn_id is None:
            return serialization.loads_control(spec.fn_blob)
        fn = self._fn_cache.get(spec.fn_id)
        if fn is None:
            blob = spec.fn_blob
            if blob is None:
                blob = self.runtime.control("get_fn_blob", spec.fn_id)
                if blob is None:
                    raise RuntimeError(
                        f"function {spec.fn_id.hex()} not in the driver "
                        "function table")
            fn = serialization.loads_control(blob)
            self._fn_cache[spec.fn_id] = fn
        return fn

    # -- task execution -----------------------------------------------------

    def _run_task(self, msg: RunTask, deliver=None) -> None:
        spec = msg.spec
        trace_ctx = getattr(spec, "trace_ctx", None)
        if trace_ctx is not None:
            # Execute span + context install: nested submits inside the
            # task join the same trace (reference: tracing_helper.py:181).
            from ray_tpu.util import tracing
            with tracing.task_span(trace_ctx, spec.name,
                                   spec.task_id.hex()):
                self._run_task_inner(msg, deliver)
        else:
            self._run_task_inner(msg, deliver)

    def _run_task_inner(self, msg: RunTask, deliver=None) -> None:
        spec = msg.spec
        rt = self.runtime
        rt.current_task_id = spec.task_id
        _tident = threading.get_ident()
        rt.thread_tasks[_tident] = (spec.task_id.hex(), spec.name)
        # Actor tasks may stash zero-copy arg views in actor state, so their
        # backing shm segments live as long as the actor.
        is_actor_task = (spec.create_actor_id is not None
                         or spec.actor_id is not None)
        if is_actor_task:
            keepalives = self._actor_keepalives
            # Set before __init__ runs so gets inside the constructor pin
            # with actor-lifetime (retain) semantics.
            if spec.create_actor_id is not None:
                rt.current_actor_id = spec.create_actor_id
        else:
            keepalives = []
            rt.begin_task_reads()
        results: List[Tuple[ObjectID, tuple]] = []
        error = None
        is_app_error = False
        import time as _time
        t0 = _time.monotonic()
        borrows: list = []
        try:
            if spec.runtime_env and spec.runtime_env.get("env_vars"):
                os.environ.update(spec.runtime_env["env_vars"])
            # Refs unpickled out of the args are borrows: tracked so
            # still-alive ones escalate to owner pinning at task end.
            rt.begin_arg_borrows()
            try:
                args = [_materialize(d, keepalives, rt)
                        for d in msg.resolved_args]
                kwargs = {k: _materialize(d, keepalives, rt)
                          for k, d in msg.resolved_kwargs.items()}
            finally:
                borrows = rt.end_arg_borrows()
            if spec.create_actor_id is not None:
                try:
                    cls = self._load_fn(spec)
                    self.actor_instance = cls(*args, **kwargs)
                except BaseException as init_exc:  # noqa: BLE001
                    self._actor_init_error = init_exc
                    raise
                finally:
                    self._actor_ready.set()
                self.actor_id = spec.create_actor_id
                rt.current_actor_id = spec.create_actor_id
                rt.send(ActorStateMsg(spec.create_actor_id, "alive",
                                      direct_addr=self._direct_addr()))
                value_list = [None] * len(spec.return_ids)
            elif spec.actor_id is not None:
                if self.actor_instance is None:
                    # No timeout: __init__ may legitimately take as long as
                    # a large-model load/compile on a TPU slice.
                    self._actor_ready.wait()
                if self.actor_instance is None:
                    cause = getattr(self, "_actor_init_error", None)
                    raise RuntimeError(
                        f"actor __init__ failed: {cause!r}" if cause
                        else "actor instance not initialized")
                if spec.method_name is None and spec.fn_blob is not None:
                    # __ray_call__-style apply: run fn(actor_instance, ...)
                    # on the actor's worker (used by compiled DAG loops).
                    fn = serialization.loads_control(spec.fn_blob)
                    call = lambda: fn(self.actor_instance, *args, **kwargs)  # noqa: E731
                else:
                    method = getattr(self.actor_instance, spec.method_name)
                    call = lambda: method(*args, **kwargs)  # noqa: E731
                if getattr(spec, "streaming", False):
                    # Streaming actor method: yielded items publish
                    # one-by-one (reference: streaming actor calls).
                    self._run_stream(call, spec, rt, results)
                    value_list = []
                else:
                    value_list = self._split_returns(call(), spec)
                call = None
            elif spec.streaming:
                fn = self._load_fn(spec)
                self._run_stream(lambda: fn(*args, **kwargs), spec, rt,
                                 results)
                value_list = []
            else:
                fn = self._load_fn(spec)
                value_list = self._split_returns(fn(*args, **kwargs), spec)
            # A ref serialized into a RESULT outlives the task at its
            # consumer: the owner retains it for the result object's
            # lifetime (containment, reference: reference_counter.h:44)
            # — ContainedRefs must hit the wire BEFORE TaskDone (FIFO
            # outbox) so the retention exists before the consumer reads.
            from .api import _nested_collector
            from .protocol import ContainedRefs
            for i, oid in enumerate(spec.return_ids):
                in_result: list = []
                token = _nested_collector.set(in_result)
                try:
                    desc = _serialize_result(rt, oid, value_list[i])
                finally:
                    _nested_collector.reset(token)
                results.append((oid, desc))
                if in_result:
                    rt.send(ContainedRefs(oid, list(in_result)))
            # Release the arg/result locals so the borrow survivor check
            # in the finally sees only refs the USER kept (actor state,
            # globals) — not this frame's own temporaries.
            args = kwargs = value_list = None
        except BaseException as exc:  # noqa: BLE001 - forwarded to caller
            is_app_error = True
            wrapped = TaskError(exc, spec.name, traceback.format_exc())
            try:
                error = ("err", serialization.pack_payload(wrapped))
            except Exception:
                error = ("err", serialization.pack_payload(
                    TaskError(RuntimeError(str(exc)), spec.name,
                              traceback.format_exc())))
            if spec.create_actor_id is not None:
                rt.send(ActorStateMsg(spec.create_actor_id, "error", error))
            # Release the frame's own references (locals + the exception's
            # traceback chain) so failed tasks don't spuriously escalate
            # their arg borrows to escaped-forever.
            args = kwargs = value_list = wrapped = None  # noqa: F841
        finally:
            rt.current_task_id = None
            rt.thread_tasks.pop(_tident, None)
            if not is_actor_task:
                # Results are serialized (copied) by now; arg/get views are
                # dead, so release their arena pins before TaskDone.
                rt.flush_task_reads()
            if borrows:
                # Borrowed refs kept beyond the task (actor state etc.)
                # escalate to owner-side pinning; must hit the wire
                # BEFORE TaskDone or the owner could free first (FIFO
                # outbox preserves the order).
                rt.report_retained_borrows(borrows)
        # Metrics recorded by this task must be at the driver before the
        # task is observed complete (FIFO outbox orders the push ahead of
        # TaskDone); no-op unless something was recorded since last flush.
        try:
            from ..util.metrics import flush_on_task_done
            flush_on_task_done()
        except Exception:
            pass
        aid = spec.actor_id or spec.create_actor_id
        frame = wire.encode_task_done(
            spec.task_id.binary(), rt.worker_id.binary(),
            [(oid.binary(), desc) for oid, desc in results],
            error, is_app_error,
            aid.binary() if aid is not None else None,
            _time.monotonic() - t0)
        if deliver is not None:
            deliver(frame, spec)
        else:
            rt.send(frame)

    @staticmethod
    def _run_stream(produce, spec, rt, results) -> None:
        """Streaming generator (reference: ObjectRefStream,
        task_manager.h:86): each yielded item is published immediately
        as ObjectID.of(task_id, i); the final ("end",) marker closes the
        stream, and a mid-stream exception lands as an err descriptor at
        the failing index so the consumer raises at the right position."""
        from .api import _nested_collector
        from .protocol import ContainedRefs
        count = 0
        try:
            for item in produce():
                oid = ObjectID.of(spec.task_id, count)
                inner: list = []
                token = _nested_collector.set(inner)
                try:
                    desc = _serialize_result(rt, oid, item)
                finally:
                    _nested_collector.reset(token)
                if inner:
                    rt.send(ContainedRefs(oid, list(inner)))
                rt.send(PutFromWorker(oid, desc))
                count += 1
        except BaseException as exc:  # noqa: BLE001
            stream_err = TaskError(exc, spec.name, traceback.format_exc())
            results.append((
                ObjectID.of(spec.task_id, count),
                ("err", serialization.pack_payload(stream_err))))
        else:
            results.append((ObjectID.of(spec.task_id, count), ("end",)))

    @staticmethod
    def _split_returns(out: Any, spec) -> List[Any]:
        n = len(spec.return_ids)
        if n == 0:
            return []
        if n == 1:
            return [out]
        if not isinstance(out, (tuple, list)) or len(out) != n:
            raise ValueError(
                f"task {spec.name!r} declared num_returns={n} but returned "
                f"{type(out).__name__} of length "
                f"{len(out) if isinstance(out, (tuple, list)) else 'n/a'}")
        return list(out)

    # -- receive loop -------------------------------------------------------

    def _dispatch(self, msg) -> bool:
        """Route one received message; returns False on KillWorker."""
        rt = self.runtime
        if type(msg) is tuple:
            if msg[0] == wire.RUN_TASK:
                spec, args, kwargs = wire.decode_run_task(msg)
                if spec.max_concurrency > self._executor.size:
                    self._executor.resize(spec.max_concurrency)
                self._executor.submit(self._run_task,
                                      RunTask(spec, args, kwargs))
                return True
            raise ValueError(f"unknown wire frame tag {msg[0]!r}")
        if isinstance(msg, RunTask):
            if msg.spec.max_concurrency > self._executor.size:
                self._executor.resize(msg.spec.max_concurrency)
            self._executor.submit(self._run_task, msg)
        elif isinstance(msg, (GetReply, WaitReply, RpcReply, AllocReply)):
            rt.deliver_reply(msg.request_id, msg)
        elif isinstance(msg, StackDumpRequest):
            # Runs on THIS (receive) thread, never the executor pool: a
            # worker wedged in user code must still answer the dump.
            try:
                from .diagnostics import capture_process_stacks
                record = capture_process_stacks(
                    rt.worker_id.hex(),
                    actor_id=self.actor_id.hex() if self.actor_id else None,
                    thread_tasks=rt.thread_tasks)
                rt.send(StackDumpReply(msg.dump_id, rt.worker_id, record))
            except Exception:  # noqa: BLE001 — diagnostics must not kill us
                traceback.print_exc()
        elif isinstance(msg, ProfileRequest):
            # Received here (not the executor pool) so a busy worker
            # still starts the capture; the capture itself blocks for
            # the whole duration, so it runs on its own thread — the
            # receive loop must keep routing replies meanwhile.
            def _capture(req=msg):
                try:
                    from ray_tpu.profiler.capture import capture_profile
                    record = capture_profile(
                        rt.worker_id.hex(), req.duration_s, hz=req.hz,
                        jax_profile=req.jax_profile,
                        driver_wall_s=req.driver_wall_s)
                except Exception as e:  # noqa: BLE001 — reported upward
                    record = {"worker_id": rt.worker_id.hex(),
                              "pid": os.getpid(), "samples": [],
                              "error": f"{type(e).__name__}: {e}"}
                rt.send(ProfileReply(req.profile_id, rt.worker_id,
                                     record))
            from . import sanitizer
            sanitizer.spawn(_capture, name="profile-capture")
        elif isinstance(msg, KillWorker):
            return False
        return True

    def run(self) -> None:
        rt = self.runtime
        rt.send(WorkerReady(rt.worker_id, os.getpid()))
        conn = rt.conn
        alive = True
        while alive:
            try:
                frame = conn.recv()
            except (EOFError, OSError):
                break
            if type(frame) is list:
                for m in frame:
                    try:
                        if not self._dispatch(m):
                            alive = False
                            break
                    except Exception:
                        # Isolate a corrupt message: dropping the rest of
                        # the batch would lose TaskDone-ordered siblings.
                        traceback.print_exc()
            else:
                try:
                    alive = self._dispatch(frame)
                except Exception:
                    traceback.print_exc()
        try:
            self._executor.shutdown()
            # Terminal metrics push rides the outbox drain below (fire
            # and forget: the recv loop that would deliver a reply is
            # gone).  Unconditional, NOT the dirty-flag-gated task-done
            # flush: samples recorded after the last task's flush (during
            # executor shutdown, teardown hooks, atexit-adjacent paths)
            # have no later completion to retry on.
            try:
                from ..util.metrics import flush_terminal
                flush_terminal()
            except Exception:
                pass
            rt.flush_and_close()
        finally:
            os._exit(0)


