"""Mesh runtime: SPMD mesh formation for distributed train worker groups.

The seed shipped a full SPMD stack (``ray_tpu/parallel``: MeshSpec, GPipe
pipeline, logical-axis sharding rules; ``ray_tpu/ops``: ring/ulysses
attention, MoE dispatch) that ``ray_tpu.train`` never used — every train
worker group ran pure data-parallel on one device per process.  This
package closes that seam:

* ``MeshConfig`` (config.py) — declarative axis sizes (or ``auto``
  factorization) carried on ``ScalingConfig``; validated against
  ``num_workers x devices_per_worker`` and consulted by the elastic
  scaling policy so the controller never forms a group the mesh cannot
  tile.
* runtime.py — worker-side global-mesh construction over the
  jax.distributed world (the controller plumbs
  ``--xla_force_host_platform_device_count`` so the CPU substrate
  exercises real multi-device meshes), mesh telemetry gauges, and the
  ``train.get_mesh()`` / ``train.shard()`` data-placement helpers.
* reshape.py — the mesh's shard layout flowed into checkpoint
  ``shard_spec``/``placement`` index algebra: a restore onto a mesh of a
  different shape is a *mesh reshape* (each process reads only the index
  slices its devices own), which is what lets an elastic drain/downsize
  re-form at the nearest valid mesh factorization instead of refusing.
"""

from .config import MeshConfig
from .reshape import (mesh_descriptor, process_index, restore_to_mesh,
                      sharding_tree)
from .runtime import (MESH_KV_KEY, addressable_param_bytes,
                      build_worker_mesh, note_mesh_axes,
                      note_param_shard_bytes, publish_mesh_status)

__all__ = [
    "MeshConfig", "build_worker_mesh", "mesh_descriptor",
    "sharding_tree", "process_index", "restore_to_mesh",
    "addressable_param_bytes", "note_param_shard_bytes",
    "note_mesh_axes", "publish_mesh_status", "MESH_KV_KEY",
]
