"""Opt-in runtime lock-order detector (``RAY_TPU_DEBUG_LOCKS=1``).

Static analysis (RT201) catches blocking calls lexically inside a
``with lock:`` block; orderings that only exist at runtime — lock A
taken in one module, lock B in another, reversed on a third path —
need instrumentation.  ``install()`` replaces ``threading.Lock`` /
``threading.RLock`` with wrappers that maintain:

* a per-thread stack of currently held locks,
* a process-wide acquisition-order graph (edge ``A -> B``: some thread
  acquired B while holding A).  A new edge that closes a cycle is a
  potential deadlock (the classic AB/BA) and is recorded as a finding
  with both acquisition sites,
* a patched ``time.sleep`` that records sleeping while holding any
  instrumented lock (the runtime twin of RT201).

Findings land in ``report()`` and are picked up by the flight recorder
(``diagnostics.write_debug_bundle`` writes ``lock_findings.json``), so
a watchdog-triggered bundle of a wedged run carries the lock story.

The detector is a debugging tool: it is conservative about overhead
(one dict lookup per acquire; stacks only on *new* edges) but is not
meant for production hot paths — hence the env-var opt-in.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_sleep = time.sleep

_installed = False

#: Frames of acquisition stack kept per new edge / finding.
_STACK_DEPTH = 6


class _State:
    def __init__(self):
        self.mu = _real_Lock()
        self.seq = 0
        # edge (holder_name, acquired_name) -> info dict
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.findings: List[Dict[str, Any]] = []
        self.seen_cycles: set = set()
        self.seen_blocking: set = set()
        # (owner_tid, lock_id) for plain Locks released by a thread
        # other than their acquirer (legal handoff pattern): the owner's
        # held list is pruned lazily at its next acquire/sleep so the
        # phantom entry cannot mint bogus edges or sleep findings.
        self.foreign_released: set = set()


_state = _State()
_tls = threading.local()


def _held() -> List[Tuple["_DebugLockBase", int]]:
    """This thread's held-lock stack: (lock, depth) entries, pruned of
    locks another thread has since released on our behalf."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    if h and _state.foreign_released:
        tid = threading.get_ident()
        with _state.mu:
            doomed = {lid for t, lid in _state.foreign_released
                      if t == tid}
            if doomed:
                _state.foreign_released -= {(tid, lid) for lid in doomed}
        if doomed:
            h[:] = [(l, d) for l, d in h if id(l) not in doomed]
    return h


def _caller_site(skip: int = 2) -> str:
    """First frame OUTSIDE this module (so with-statement acquires point
    at the user line, not at __enter__)."""
    try:
        f = sys._getframe(skip)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return "<unknown>"
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "<unknown>"


def _short_stack() -> List[str]:
    return [ln.strip().replace("\n", " | ")
            for ln in traceback.format_stack()[-_STACK_DEPTH - 2:-2]]


def _find_cycle(start: str, target: str) -> Optional[List[str]]:
    """Path ``start -> ... -> target`` through the edge graph (the new
    edge target->start then closes the cycle)."""
    adj: Dict[str, List[str]] = {}
    for a, b in _state.edges:
        adj.setdefault(a, []).append(b)
    path = [start]
    seen = {start}

    def dfs(node: str) -> bool:
        if node == target:
            return True
        for nxt in adj.get(node, ()):
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if dfs(nxt):
                return True
            path.pop()
        return False

    return path if dfs(start) else None


def _note_acquire(lock: "_DebugLockBase") -> None:
    held = _held()
    for i, (prev, depth) in enumerate(held):
        if prev is lock:  # reentrant re-acquire: no new ordering info
            held[i] = (prev, depth + 1)
            return
    site = _caller_site(3)
    new_edges = []
    with _state.mu:
        for prev, _depth in held:
            key = (prev.name, lock.name)
            info = _state.edges.get(key)
            if info is None:
                _state.edges[key] = {
                    "holder": prev.name, "acquired": lock.name,
                    "thread": threading.current_thread().name,
                    "site": site, "stack": _short_stack(), "count": 1}
                new_edges.append(key)
            else:
                info["count"] += 1
        for a, b in new_edges:
            # b already reaches a through older edges? then a->b closes
            # a cycle: two threads interleaving those orders deadlock.
            cycle = _find_cycle(b, a)
            if not cycle:
                continue
            cycle_key = frozenset(cycle)
            if cycle_key in _state.seen_cycles:
                continue
            _state.seen_cycles.add(cycle_key)
            _state.findings.append({
                "kind": "lock_cycle",
                "cycle": cycle + [b],
                "edges": [dict(_state.edges[e])
                          for e in _state.edges
                          if e[0] in cycle_key and e[1] in cycle_key],
                "thread": threading.current_thread().name,
                "site": site,
            })
    held.append((lock, 1))


def _note_release(lock: "_DebugLockBase") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        prev, depth = held[i]
        if prev is lock:
            if depth > 1:
                held[i] = (prev, depth - 1)
            else:
                del held[i]
            return


class _DebugLockBase:
    _kind = "Lock"

    def __init__(self):
        with _state.mu:
            _state.seq += 1
            n = _state.seq
        self._inner = self._make_inner()
        self.name = f"{self._kind}#{n}@{_caller_site(2)}"

    def _make_inner(self):
        return _real_Lock()

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            _note_acquire(self)
        return got

    def release(self):
        _note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        fn = getattr(self._inner, "locked", None)
        return fn() if fn is not None else False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class _DebugLock(_DebugLockBase):
    _kind = "Lock"

    # Unlike RLock, a plain Lock may legally be released by a thread
    # that did not acquire it (handoff/signal pattern).  Track the
    # acquiring thread so a foreign release queues a prune of the
    # owner's held list instead of silently leaving a phantom entry.

    def acquire(self, *args, **kwargs):
        got = super().acquire(*args, **kwargs)
        if got:
            self._owner_ident = threading.get_ident()
        return got

    def release(self):
        owner = getattr(self, "_owner_ident", None)
        self._owner_ident = None
        if owner is not None and owner != threading.get_ident():
            with _state.mu:
                _state.foreign_released.add((owner, id(self)))
            self._inner.release()
        else:
            _note_release(self)
            self._inner.release()


class _DebugRLock(_DebugLockBase):
    """RLock wrapper: also forwards the protocol Condition uses so
    ``threading.Condition(rlock)`` keeps exact reentrant semantics."""

    _kind = "RLock"

    def _make_inner(self):
        return _real_RLock()

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        _note_release(self)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _note_acquire(self)


def _debug_sleep(seconds):
    held = _held()
    if held:
        site = _caller_site(2)
        key = (site, tuple(l.name for l, _d in held))
        with _state.mu:
            if key not in _state.seen_blocking:
                _state.seen_blocking.add(key)
                _state.findings.append({
                    "kind": "blocking_under_lock",
                    "blocking_call": f"time.sleep({seconds!r})",
                    "held_locks": [l.name for l, _d in held],
                    "thread": threading.current_thread().name,
                    "site": site,
                    "stack": _short_stack(),
                })
    return _real_sleep(seconds)


# -- public API -------------------------------------------------------------


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` (locks created from now on are
    instrumented) and ``time.sleep``.  Idempotent."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _DebugLock  # type: ignore[misc]
    threading.RLock = _DebugRLock  # type: ignore[misc]
    time.sleep = _debug_sleep


def uninstall() -> None:
    """Restore the real primitives (already-created wrappers keep
    working: they delegate to real locks)."""
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _real_Lock  # type: ignore[misc]
    threading.RLock = _real_RLock  # type: ignore[misc]
    time.sleep = _real_sleep


def is_installed() -> bool:
    return _installed


def findings() -> List[Dict[str, Any]]:
    with _state.mu:
        return [dict(f) for f in _state.findings]


def clear() -> None:
    with _state.mu:
        _state.edges.clear()
        _state.findings.clear()
        _state.seen_cycles.clear()
        _state.seen_blocking.clear()
        _state.foreign_released.clear()


def report() -> Dict[str, Any]:
    """Snapshot for the flight recorder's ``lock_findings.json``."""
    with _state.mu:
        return {
            "installed": _installed,
            "pid": os.getpid(),
            "edges": len(_state.edges),
            "findings": [dict(f) for f in _state.findings],
        }
