"""Static-analysis suite: per-rule true-positive/clean-negative pairs,
noqa suppression, the repo self-lint gate, the lint CLI, and the runtime
lock-order detector (cycle seeding + flight-recorder integration)."""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from ray_tpu.devtools import lint_paths, lint_source
from ray_tpu.devtools.lint import format_json, format_text


def rule_ids(src, internal=False, path="<snippet>"):
    return [f.rule for f in lint_source(src, path=path, internal=internal)]


# -- user rules (RT1xx) -----------------------------------------------------


class TestNestedGetRT101:
    BAD = """
import ray_tpu

@ray_tpu.remote
def outer(ref):
    return ray_tpu.get(ref) + 1
"""

    GOOD = """
import ray_tpu

@ray_tpu.remote
def outer(x):
    return x + 1

def driver(ref):
    return ray_tpu.get(ref)
"""

    def test_positive(self):
        findings = lint_source(self.BAD)
        assert [f.rule for f in findings] == ["RT101"]
        assert findings[0].line == 6
        assert "outer" in findings[0].message

    def test_actor_method_positive(self):
        src = """
import ray_tpu

@ray_tpu.remote
class A:
    def m(self, ref):
        return ray_tpu.get(ref)
"""
        assert rule_ids(src) == ["RT101"]

    def test_negative(self):
        assert rule_ids(self.GOOD) == []

    def test_suppression(self):
        patched = self.BAD.replace(
            "return ray_tpu.get(ref) + 1",
            "return ray_tpu.get(ref) + 1  # ray-tpu: noqa[RT101]")
        assert rule_ids(patched) == []

    def test_suppression_other_rule_does_not_mask(self):
        patched = self.BAD.replace(
            "return ray_tpu.get(ref) + 1",
            "return ray_tpu.get(ref) + 1  # ray-tpu: noqa[RT102]")
        assert rule_ids(patched) == ["RT101"]

    def test_bare_noqa_suppresses(self):
        patched = self.BAD.replace(
            "return ray_tpu.get(ref) + 1",
            "return ray_tpu.get(ref) + 1  # ray-tpu: noqa")
        assert rule_ids(patched) == []


class TestGetInLoopRT102:
    BAD = """
import ray_tpu

def driver(refs):
    out = []
    for r in refs:
        out.append(ray_tpu.get(r))
    return out
"""

    def test_positive(self):
        findings = lint_source(self.BAD)
        assert [f.rule for f in findings] == ["RT102"]
        assert findings[0].line == 7

    def test_subscript_positive(self):
        src = """
import ray_tpu

def driver(refs):
    for i in range(len(refs)):
        print(ray_tpu.get(refs[i]))
"""
        assert rule_ids(src) == ["RT102"]

    def test_wait_derived_negative(self):
        src = """
import ray_tpu

def driver(refs):
    done, pending = ray_tpu.wait(refs, num_returns=len(refs))
    for r in done:
        print(ray_tpu.get(r))
"""
        assert rule_ids(src) == []

    def test_streaming_generator_negative(self):
        src = """
import ray_tpu

def driver(h, x):
    for item in h.remote(x):
        print(ray_tpu.get(item))
"""
        assert rule_ids(src) == []


class TestLargeCaptureRT103:
    def test_module_array_positive(self):
        src = """
import ray_tpu
import numpy as np

TABLE = np.zeros((1000, 1000))

@ray_tpu.remote
def f(i):
    return TABLE[i].sum()
"""
        assert rule_ids(src) == ["RT103"]

    def test_large_literal_arg_positive(self):
        big = "[" + ", ".join("0" for _ in range(80)) + "]"
        src = f"""
import ray_tpu

def driver(f):
    return f.remote({big})
"""
        assert rule_ids(src) == ["RT103"]

    def test_put_negative(self):
        src = """
import ray_tpu
import numpy as np

TABLE = np.zeros((1000, 1000))

@ray_tpu.remote
def f(table, i):
    return table[i].sum()

def driver():
    ref = ray_tpu.put(TABLE)
    return f.remote(ref, 0)
"""
        assert rule_ids(src) == []


class TestUnserializableCaptureRT104:
    def test_module_lock_positive(self):
        src = """
import ray_tpu
import threading

LOCK = threading.Lock()

@ray_tpu.remote
def f():
    with LOCK:
        return 1
"""
        assert rule_ids(src) == ["RT104"]

    def test_direct_arg_positive(self):
        src = """
import ray_tpu

def driver(f):
    return f.remote(open("/tmp/x"))
"""
        assert rule_ids(src) == ["RT104"]

    def test_local_lock_negative(self):
        src = """
import ray_tpu
import threading

@ray_tpu.remote
def f():
    lock = threading.Lock()
    with lock:
        return 1
"""
        assert rule_ids(src) == []

    def test_actor_state_negative(self):
        # Locks in actor state never cross a process boundary: fine.
        src = """
import ray_tpu
import threading

LOCK = threading.Lock()

@ray_tpu.remote
class A:
    def m(self):
        with LOCK:
            return 1
"""
        assert rule_ids(src) == []


class TestActorSelfCallRT105:
    BAD = """
import ray_tpu

@ray_tpu.remote
class A:
    def step(self):
        return 1

    def run(self):
        return self.step.remote()
"""

    def test_positive(self):
        findings = lint_source(self.BAD)
        assert [f.rule for f in findings] == ["RT105"]
        assert "self.step" in findings[0].message

    def test_other_handle_negative(self):
        src = """
import ray_tpu

@ray_tpu.remote
class A:
    def __init__(self, other):
        self.other = other

    def run(self):
        return self.other.step.remote()
"""
        assert rule_ids(src) == []


# -- internal rules (RT2xx) -------------------------------------------------


class TestBlockingUnderLockRT201:
    BAD = """
import threading
import time

lock = threading.Lock()

def f():
    with lock:
        time.sleep(1)
"""

    def test_positive(self):
        findings = lint_source(self.BAD, internal=True)
        assert [f.rule for f in findings] == ["RT201"]
        assert "time.sleep" in findings[0].message

    def test_user_scope_skips_internal_rules(self):
        assert rule_ids(self.BAD, internal=False) == []

    def test_negative_outside_lock(self):
        src = """
import threading
import time

lock = threading.Lock()

def f():
    with lock:
        x = 1
    time.sleep(1)
"""
        assert rule_ids(src, internal=True) == []

    def test_condition_wait_idiom_negative(self):
        src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)

    def f(self):
        with self._lock:
            self._wake.wait(1.0)
"""
        assert rule_ids(src, internal=True) == []

    def test_event_wait_under_lock_positive(self):
        src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._evt = threading.Event()

    def f(self):
        with self._lock:
            self._evt.wait(1.0)
"""
        assert rule_ids(src, internal=True) == ["RT201"]

    def test_str_join_negative_thread_join_positive(self):
        src = """
import threading

lock = threading.Lock()

def f(parts, t):
    with lock:
        s = ",".join(parts)
        t.join(5)
    return s
"""
        findings = lint_source(src, internal=True)
        assert [f.rule for f in findings] == ["RT201"]
        assert ".join()" in findings[0].message
        assert findings[0].line == 9

    def test_with_line_anchor_suppression(self):
        patched = self.BAD.replace("with lock:",
                                   "with lock:  # ray-tpu: noqa[RT201]")
        assert rule_ids(patched, internal=True) == []


class TestSwallowedExceptionRT202:
    PATH = "ray_tpu/_private/runtime.py"
    BAD = """
def f(x):
    try:
        x()
    except Exception:
        pass
"""

    def test_positive(self):
        assert rule_ids(self.BAD, internal=True, path=self.PATH) == ["RT202"]

    def test_non_control_plane_negative(self):
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/serve/api.py") == []

    def test_handled_negative(self):
        src = """
from ray_tpu.util import telemetry

def f(x):
    try:
        x()
    except Exception as e:
        telemetry.note_swallowed("runtime.f", e)
"""
        assert rule_ids(src, internal=True, path=self.PATH) == []

    def test_narrow_except_negative(self):
        src = """
def f(x):
    try:
        x()
    except ValueError:
        pass
"""
        assert rule_ids(src, internal=True, path=self.PATH) == []


class TestWallClockDurationRT203:
    def test_sub_positive(self):
        src = """
import time

def f(work):
    t0 = time.time()
    work()
    return time.time() - t0
"""
        ids = rule_ids(src, internal=True)
        assert ids == ["RT203"]

    def test_deadline_compare_positive(self):
        src = """
import time

def f(deadline):
    return time.time() > deadline
"""
        assert rule_ids(src, internal=True) == ["RT203"]

    def test_monotonic_negative(self):
        src = """
import time

def f(work):
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0
"""
        assert rule_ids(src, internal=True) == []

    def test_timestamp_record_negative(self):
        src = """
import time

def f():
    return {"time": time.time()}
"""
        assert rule_ids(src, internal=True) == []


class TestTelemetrySeriesRT204:
    def test_unknown_name_positive(self):
        src = """
from ray_tpu.util import telemetry

def f():
    telemetry.inc("ray_tpu_serve_bogus_total")
"""
        assert rule_ids(src, internal=True) == ["RT204"]

    def test_catalog_name_negative(self):
        src = """
from ray_tpu.util import telemetry

def f():
    telemetry.inc("ray_tpu_serve_requests_total")
    telemetry.set_gauge("ray_tpu_llm_active_slots", 1.0)
"""
        assert rule_ids(src, internal=True) == []


class TestAtomicPublishRT206:
    BAD = """
import json

def commit(path, manifest):
    with open(path, "w") as f:
        json.dump(manifest, f)
"""

    GOOD = """
import json
import os

def commit(path, manifest):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)
"""

    def test_positive_in_checkpoint_module(self):
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/checkpoint/manager.py") == ["RT206"]

    def test_tmp_plus_replace_negative(self):
        assert rule_ids(self.GOOD, internal=True,
                        path="ray_tpu/checkpoint/manager.py") == []

    def test_keyword_mode_positive(self):
        src = self.BAD.replace('open(path, "w")', 'open(path, mode="w")')
        assert rule_ids(src, internal=True,
                        path="ray_tpu/checkpoint/manager.py") == ["RT206"]

    def test_out_of_scope_module_negative(self):
        # Only checkpoint/control-plane modules publish commit records;
        # a bare open() elsewhere (bench output, debug dumps) is fine.
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/serve/api.py") == []

    def test_read_mode_negative(self):
        src = """
def load(path):
    with open(path, "rb") as f:
        return f.read()
"""
        assert rule_ids(src, internal=True,
                        path="ray_tpu/checkpoint/format.py") == []

    def test_suppression(self):
        patched = self.BAD.replace(
            'with open(path, "w") as f:',
            'with open(path, "w") as f:  # ray-tpu: noqa[RT206]')
        assert rule_ids(patched, internal=True,
                        path="ray_tpu/checkpoint/manager.py") == []


class TestDevicePutAliasRT207:
    BAD = """
import jax
import numpy as np

def dispatch(sharding):
    buf = np.zeros((8, 128), np.float32)
    x = jax.device_put(buf, sharding)
    buf[0] = 1.0
    return x
"""

    GOOD = """
import jax
import numpy as np

def dispatch(sharding):
    buf = np.zeros((8, 128), np.float32)
    x = jax.device_put(np.ascontiguousarray(buf), sharding)
    buf[0] = 1.0
    return x
"""

    def test_subscript_mutation_positive(self):
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/train/mesh/runtime.py") == ["RT207"]

    def test_augassign_mutation_positive(self):
        src = self.BAD.replace("buf[0] = 1.0", "buf += 1.0")
        assert rule_ids(src, internal=True,
                        path="ray_tpu/parallel/spmd.py") == ["RT207"]

    def test_copy_dispatch_negative(self):
        assert rule_ids(self.GOOD, internal=True,
                        path="ray_tpu/train/mesh/runtime.py") == []

    def test_fill_then_dispatch_negative(self):
        # All mutation happens BEFORE the dispatch — the normal buffer
        # init pattern; nothing can corrupt the device value.
        src = """
import jax
import numpy as np

def dispatch(sharding):
    buf = np.zeros((8, 128), np.float32)
    buf[0] = 1.0
    return jax.device_put(buf, sharding)
"""
        assert rule_ids(src, internal=True,
                        path="ray_tpu/train/mesh/runtime.py") == []

    def test_rebinding_is_not_mutation(self):
        # buf = ... after dispatch rebinds the name; the device value's
        # aliased buffer is unchanged.
        src = self.BAD.replace("buf[0] = 1.0", "buf = buf + 1.0")
        assert rule_ids(src, internal=True,
                        path="ray_tpu/train/mesh/runtime.py") == []

    def test_scope_inferred_from_jax_context(self):
        # Scoping rides the shared RT5xx jax-context detection (any
        # module importing jax), not the old hard-coded directory
        # list: the same aliasing hazard fires outside mesh/pipeline
        # dirs too.
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/serve/api.py") == ["RT207"]

    def test_out_of_scope_module_negative(self):
        # A module with no jax context (a device_put on some unrelated
        # object, jax never imported) stays out of scope.
        src = self.BAD.replace("import jax\n", "").replace(
            "jax.device_put", "backend.device_put")
        assert rule_ids(src, internal=True,
                        path="ray_tpu/serve/api.py") == []

    def test_suppression(self):
        patched = self.BAD.replace(
            "x = jax.device_put(buf, sharding)",
            "x = jax.device_put(buf, sharding)  # ray-tpu: noqa[RT207]")
        assert rule_ids(patched, internal=True,
                        path="ray_tpu/train/mesh/runtime.py") == []


class TestProtocolCoverageRT205:
    def test_unhandled_message_positive(self, tmp_path):
        private = tmp_path / "_private"
        private.mkdir()
        (private / "protocol.py").write_text(
            "from dataclasses import dataclass\n\n\n"
            "@dataclass\nclass Handled:\n    x: int = 0\n\n\n"
            "@dataclass\nclass Orphan:\n    y: int = 0\n")
        (private / "worker.py").write_text(
            "def route(msg):\n"
            "    if isinstance(msg, Handled):\n"
            "        return True\n")
        res = lint_paths([str(private)], internal=True)
        assert [f.rule for f in res.findings] == ["RT205"]
        assert "Orphan" in res.findings[0].message


# -- concurrency rules (RT4xx) ----------------------------------------------


class TestInconsistentGuardRT401:
    BAD = """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []

    def put(self, x):
        with self._lock:
            self._q.append(x)

    def drain(self):
        out, self._q = self._q, []
        return out
"""

    GOOD = """
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []

    def put(self, x):
        with self._lock:
            self._q.append(x)

    def drain(self):
        with self._lock:
            out, self._q = self._q, []
        return out
"""

    def test_positive_anchored_at_first_bare_site(self):
        findings = lint_source(self.BAD, internal=True)
        assert [f.rule for f in findings] == ["RT401"]
        f = findings[0]
        assert f.line == 14  # drain()'s bare swap
        assert "self._q" in f.message and "bare" in f.message

    def test_one_finding_per_attr_counts_all_bare_sites(self):
        src = self.BAD + """
    def peek(self):
        return len(self._q)
"""
        findings = lint_source(src, internal=True)
        assert [f.rule for f in findings] == ["RT401"]
        assert "3 bare site(s)" in findings[0].message

    def test_negative_all_sites_guarded(self):
        assert rule_ids(self.GOOD, internal=True) == []

    def test_user_scope_skips_internal_rules(self):
        assert rule_ids(self.BAD, internal=False) == []

    def test_ctor_accesses_are_not_bare_sites(self):
        # __init__ publishes nothing: its bare writes alone must not
        # turn every guarded attribute into a finding.
        assert "RT401" not in rule_ids(self.GOOD, internal=True)

    def test_suppression_at_anchor_silences_whole_finding(self):
        patched = self.BAD.replace(
            "out, self._q = self._q, []",
            "out, self._q = self._q, []  # ray-tpu: noqa[RT401]")
        assert rule_ids(patched, internal=True) == []

    def test_suppressed_counts_reported(self):
        patched = self.BAD.replace(
            "out, self._q = self._q, []",
            "out, self._q = self._q, []  # ray-tpu: noqa[RT401]")
        counts = {}
        lint_source(patched, internal=True, suppressed_counts=counts)
        assert counts == {"RT401": 1}


class TestCheckThenActRT402:
    BAD = """
import threading

class Election:
    def __init__(self):
        self._lock = threading.Lock()
        self._leader = None

    def set_leader(self, who):
        with self._lock:
            self._leader = who

    def try_claim(self, me):
        if self._leader is None:
            self._leader = me
"""

    GOOD = """
import threading

class Election:
    def __init__(self):
        self._lock = threading.Lock()
        self._leader = None

    def set_leader(self, who):
        with self._lock:
            self._leader = who

    def try_claim(self, me):
        with self._lock:
            if self._leader is None:
                self._leader = me
"""

    def test_positive(self):
        findings = lint_source(self.BAD, internal=True)
        # The bare check-then-act is ALSO an inconsistent-guard site;
        # both defects are real and both must be named.
        assert sorted(f.rule for f in findings) == ["RT401", "RT402"]
        f = next(f for f in findings if f.rule == "RT402")
        assert "check-then-act" in f.message
        assert "self._leader" in f.message

    def test_negative_inside_lock(self):
        assert rule_ids(self.GOOD, internal=True) == []

    def test_suppression(self):
        patched = self.BAD.replace(
            "if self._leader is None:",
            "if self._leader is None:  # ray-tpu: noqa[RT401,RT402]")
        assert rule_ids(patched, internal=True) == []


class TestReleaseMidIterationRT403:
    BAD = """
import threading

class Notifier:
    def __init__(self):
        self._lock = threading.Lock()
        self._waiters = {}

    def notify_all(self, cb):
        with self._lock:
            for k in self._waiters:
                self._lock.release()
                cb(k)
                self._lock.acquire()
"""

    def test_positive(self):
        findings = lint_source(self.BAD, internal=True)
        # The bare re-acquire at the loop tail is ALSO an RT301
        # (not released on every path) — both defects are real.
        assert sorted(f.rule for f in findings) == ["RT301", "RT403"]
        f = next(f for f in findings if f.rule == "RT403")
        assert "self._waiters" in f.message
        assert "snapshot" in f.message

    def test_condition_wait_releases_aliased_lock(self):
        # cond.wait() releases the Condition's lock; through the alias
        # map that is the same lock guarding the iteration.
        src = """
import threading

class Notifier:
    def __init__(self):
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._items = []

    def drain(self):
        with self._lock:
            for it in self._items:
                self._wake.wait(0.1)
"""
        assert rule_ids(src, internal=True) == ["RT403"]

    def test_snapshot_then_iterate_negative(self):
        src = """
import threading

class Notifier:
    def __init__(self):
        self._lock = threading.Lock()
        self._waiters = {}

    def notify_all(self, cb):
        with self._lock:
            waiters = list(self._waiters)
        for k in waiters:
            cb(k)
"""
        assert rule_ids(src, internal=True) == []

    def test_suppression(self):
        patched = self.BAD.replace(
            "self._lock.release()",
            "self._lock.release()  # ray-tpu: noqa[RT403]").replace(
            "self._lock.acquire()",
            "self._lock.acquire()  # ray-tpu: noqa[RT301]")
        assert rule_ids(patched, internal=True) == []


class TestHotLockCallbackRT404:
    PATH = "ray_tpu/_private/scheduler.py"
    BAD = """
import threading

class Sched:
    def __init__(self):
        self._lock = threading.Lock()
        self._ready = []

    def pop(self):
        with self._lock:
            t = self._ready.pop()
            self.on_stage(t)
        return t
"""

    GOOD = """
import threading

class Sched:
    def __init__(self):
        self._lock = threading.Lock()
        self._ready = []

    def pop(self):
        with self._lock:
            t = self._ready.pop()
        self.on_stage(t)
        return t
"""

    def test_callback_under_lock_positive(self):
        findings = lint_source(self.BAD, internal=True, path=self.PATH)
        assert [f.rule for f in findings] == ["RT404"]
        assert "callback" in findings[0].message
        assert "off-lock publish" in findings[0].message

    def test_publish_under_lock_positive(self):
        src = """
import threading
from ray_tpu.util import telemetry

class Sched:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
            telemetry.inc("ray_tpu_serve_requests_total")
"""
        findings = lint_source(src, internal=True, path=self.PATH)
        assert [f.rule for f in findings] == ["RT404"]
        assert "publish" in findings[0].message

    def test_after_release_negative(self):
        assert rule_ids(self.GOOD, internal=True, path=self.PATH) == []

    def test_non_hot_module_negative(self):
        # Only scheduler/node/store/metrics locks sit on the decision
        # path of every task; elsewhere the pattern is fine.
        assert rule_ids(self.BAD, internal=True,
                        path="ray_tpu/serve/api.py") == []

    def test_suppression(self):
        patched = self.BAD.replace(
            "self.on_stage(t)",
            "self.on_stage(t)  # ray-tpu: noqa[RT404]")
        assert rule_ids(patched, internal=True, path=self.PATH) == []


class TestLockedSuffixRT405:
    BAD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump_locked(self):
        self._n += 1

    def kick(self):
        self._bump_locked()
"""

    GOOD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump_locked(self):
        self._n += 1

    def kick(self):
        with self._lock:
            self._bump_locked()
"""

    def test_positive(self):
        findings = lint_source(self.BAD, internal=True)
        assert [f.rule for f in findings] == ["RT405"]
        assert "_bump_locked" in findings[0].message

    def test_negative_called_under_lock(self):
        assert rule_ids(self.GOOD, internal=True) == []

    def test_locked_contract_feeds_guarded_inference(self):
        # _bump_locked() runs under the caller's lock by contract, so
        # its write counts as guarded — a bare read elsewhere is RT401.
        src = self.GOOD + """
    def peek(self):
        return self._n
"""
        assert rule_ids(src, internal=True) == ["RT401"]

    def test_suppression(self):
        patched = self.BAD.replace(
            "        self._bump_locked()",
            "        self._bump_locked()  # ray-tpu: noqa[RT405]")
        assert rule_ids(patched, internal=True) == []


# -- repo gates -------------------------------------------------------------


class TestSelfLint:
    def test_ray_tpu_tree_is_clean(self):
        """The tier-1 self-lint gate: the framework passes its own
        static analysis with zero findings."""
        import ray_tpu
        pkg = os.path.dirname(os.path.abspath(ray_tpu.__file__))
        res = lint_paths([pkg])
        assert res.files_checked > 100
        assert res.ok, "\n" + format_text(res)

    def test_train_mesh_subsystem_is_covered(self):
        """train/mesh/ is inside the self-lint gate from day one: its
        files are walked with the internal (RT2xx/RT3xx) rules on, and
        they pass clean."""
        import ray_tpu
        pkg = os.path.dirname(os.path.abspath(ray_tpu.__file__))
        res = lint_paths([os.path.join(pkg, "train", "mesh")])
        assert res.files_checked >= 4
        assert res.ok, "\n" + format_text(res)

    def test_bad_corpus_fails(self):
        res_findings = lint_source(TestNestedGetRT101.BAD)
        assert res_findings, "bad corpus must produce findings"


class TestOutputAndCli:
    def test_json_format_roundtrip(self):
        findings = lint_source(TestGetInLoopRT102.BAD, path="bad.py")
        from ray_tpu.devtools.lint import LintResult
        doc = json.loads(format_json(LintResult(findings, 1)))
        assert doc["version"] == 1
        assert doc["files_checked"] == 1
        assert doc["findings"][0]["rule"] == "RT102"
        assert doc["findings"][0]["path"] == "bad.py"
        assert doc["findings"][0]["line"] == 7

    def test_cli_exit_codes(self, tmp_path):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        bad = tmp_path / "user_code.py"
        bad.write_text(TestNestedGetRT101.BAD)
        runner = CliRunner()
        r = runner.invoke(cli, ["lint", str(bad)])
        assert r.exit_code == 1
        assert "RT101" in r.output
        good = tmp_path / "ok_code.py"
        good.write_text("x = 1\n")
        r = runner.invoke(cli, ["lint", str(good)])
        assert r.exit_code == 0
        r = runner.invoke(cli, ["lint", "--format", "json", str(bad)])
        assert r.exit_code == 1
        assert json.loads(r.output)["findings"][0]["rule"] == "RT101"

    def test_nonexistent_path_is_loud(self, tmp_path):
        """A typo'd path must not turn the lint gate into a green
        '0 findings in 0 files' no-op."""
        res = lint_paths([str(tmp_path / "no_such_dir")])
        assert [f.rule for f in res.findings] == ["RT002"]
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        r = CliRunner().invoke(cli, ["lint", str(tmp_path / "nope.py")])
        assert r.exit_code == 1
        assert "RT002" in r.output

    def test_cli_list_rules(self):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        r = CliRunner().invoke(cli, ["lint", "--list-rules"])
        assert r.exit_code == 0
        for rid in ("RT101", "RT102", "RT103", "RT104", "RT105",
                    "RT201", "RT202", "RT203", "RT204", "RT205"):
            assert rid in r.output


# -- runtime lock-order detector --------------------------------------------


@pytest.fixture
def lockdebug():
    from ray_tpu.devtools import lockdebug as mod
    mod.install()
    mod.clear()
    try:
        yield mod
    finally:
        mod.clear()
        mod.uninstall()


class TestLockDebug:
    def test_ab_ba_cycle_reported_and_in_bundle(self, lockdebug, tmp_path):
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        assert type(lock_a).__name__ == "_DebugLock"
        t1_done = threading.Event()

        def t1():
            with lock_a:
                with lock_b:
                    pass
            t1_done.set()

        def t2():
            t1_done.wait(5.0)
            with lock_b:
                with lock_a:
                    pass

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start()
        th2.start()
        th1.join(5.0)
        th2.join(5.0)

        cycles = [f for f in lockdebug.findings()
                  if f["kind"] == "lock_cycle"]
        assert len(cycles) == 1, lockdebug.findings()
        cyc = cycles[0]
        assert lock_a.name in cyc["cycle"] and lock_b.name in cyc["cycle"]
        assert cyc["edges"], "cycle finding must carry its edges"

        # The finding reaches the flight recorder bundle.
        from ray_tpu._private.diagnostics import write_debug_bundle

        class _Rt:
            session_dir = str(tmp_path)
        path = write_debug_bundle(_Rt(), "lock_cycle_test",
                                  capture_stacks=False)
        with open(os.path.join(path, "lock_findings.json")) as f:
            doc = json.load(f)
        assert doc["installed"] is True
        assert any(f["kind"] == "lock_cycle" for f in doc["findings"])
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        assert "lock_findings.json" in manifest["contents"]

    def test_consistent_order_no_cycle(self, lockdebug):
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        for _ in range(3):
            with lock_a:
                with lock_b:
                    pass
        assert [f for f in lockdebug.findings()
                if f["kind"] == "lock_cycle"] == []

    def test_sleep_under_lock_reported(self, lockdebug):
        lock = threading.Lock()
        with lock:
            time.sleep(0.001)
        blocked = [f for f in lockdebug.findings()
                   if f["kind"] == "blocking_under_lock"]
        assert len(blocked) == 1
        assert lock.name in blocked[0]["held_locks"]
        # Same site again: deduplicated, not re-reported.
        with lock:
            time.sleep(0.001)

    def test_sleep_without_lock_clean(self, lockdebug):
        time.sleep(0.001)
        assert [f for f in lockdebug.findings()
                if f["kind"] == "blocking_under_lock"] == []

    def test_rlock_reentrancy_no_self_cycle(self, lockdebug):
        r = threading.RLock()
        with r:
            with r:
                pass
        assert lockdebug.findings() == []

    def test_cross_thread_release_leaves_no_phantom(self, lockdebug):
        """A plain Lock released by a different thread (legal handoff)
        must not leave a phantom held entry that mints bogus edges and
        sleep-under-lock findings for the acquiring thread."""
        handoff = threading.Lock()
        other = threading.Lock()
        handoff.acquire()  # main thread acquires...

        t = threading.Thread(target=handoff.release)  # ...helper releases
        t.start()
        t.join(5.0)

        with other:           # would record handoff->other if phantom
            time.sleep(0.001)  # would record blocking_under_lock twice
        blocked = [f for f in lockdebug.findings()
                   if f["kind"] == "blocking_under_lock"]
        assert len(blocked) == 1
        assert blocked[0]["held_locks"] == [other.name]
        assert not any(f["kind"] == "lock_cycle"
                       for f in lockdebug.findings())

    def test_condition_on_wrapped_lock_works(self, lockdebug):
        cond = threading.Condition()
        with cond:
            cond.wait(timeout=0.01)
        hit = []

        def waiter():
            with cond:
                hit.append(cond.wait(timeout=5.0))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(5.0)
        assert hit == [True]


# -- suppression reporting & CLI surface ------------------------------------


class TestSuppressionReporting:
    def test_lint_paths_counts_and_formats_report_debt(self, tmp_path):
        pkg = tmp_path / "ray_tpu"  # inside a ray_tpu tree -> internal
        pkg.mkdir()
        src = TestInconsistentGuardRT401.BAD.replace(
            "out, self._q = self._q, []",
            "out, self._q = self._q, []  # ray-tpu: noqa[RT401]")
        (pkg / "mod.py").write_text(src)
        res = lint_paths([str(pkg)])
        assert res.ok
        assert res.suppressed == {"RT401": 1}
        text = format_text(res)
        assert "1 suppressed (RT401×1)" in text
        doc = json.loads(format_json(res))
        assert doc["suppressed"] == {"RT401": 1}

    def test_repo_self_lint_reports_suppressions(self):
        """The zero-findings gate holds BECAUSE justified suppressions
        are counted, not hidden: the tree carries RT4xx noqa debt and
        the run must say so."""
        import ray_tpu
        pkg = os.path.dirname(os.path.abspath(ray_tpu.__file__))
        res = lint_paths([pkg])
        assert res.ok
        assert res.suppressed.get("RT401", 0) > 0
        assert "suppressed" in format_text(res)


class TestCliChangedAndFormats:
    def test_github_format_annotations(self, tmp_path):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        bad = tmp_path / "user_code.py"
        bad.write_text(TestNestedGetRT101.BAD)
        r = CliRunner().invoke(cli, ["lint", "--format", "github",
                                     str(bad)])
        assert r.exit_code == 1
        assert r.output.startswith("::error file=")
        assert "title=RT101" in r.output
        good = tmp_path / "ok_code.py"
        good.write_text("x = 1\n")
        r = CliRunner().invoke(cli, ["lint", "--format", "github",
                                     str(good)])
        assert r.exit_code == 0

    def _seed_repo(self, path):
        import subprocess

        def git(*args):
            subprocess.run(["git", *args], cwd=str(path), check=True,
                           capture_output=True)
        git("init", "-q")
        git("config", "user.email", "lint@test")
        git("config", "user.name", "lint test")
        (path / "ok.py").write_text("x = 1\n")
        git("add", ".")
        git("commit", "-q", "-m", "seed")
        return git

    def test_changed_lints_only_the_diff(self, tmp_path, monkeypatch):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        self._seed_repo(tmp_path)
        (tmp_path / "bad.py").write_text(TestNestedGetRT101.BAD)
        monkeypatch.chdir(tmp_path)
        r = CliRunner().invoke(cli, ["lint", "--changed"])
        assert r.exit_code == 1
        assert "RT101" in r.output and "bad.py" in r.output
        assert "ok.py" not in r.output  # committed-clean file skipped

    def test_changed_clean_worktree_is_green(self, tmp_path, monkeypatch):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        self._seed_repo(tmp_path)
        monkeypatch.chdir(tmp_path)
        r = CliRunner().invoke(cli, ["lint", "--changed"])
        assert r.exit_code == 0
        assert "no changed .py files" in r.output

    def test_changed_bad_base_ref_is_loud(self, tmp_path, monkeypatch):
        """A typo'd --base must exit 2 loudly, never green-no-op."""
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        self._seed_repo(tmp_path)
        monkeypatch.chdir(tmp_path)
        r = CliRunner().invoke(cli, ["lint", "--changed", "--base",
                                     "no_such_ref"])
        assert r.exit_code == 2
        assert "--changed:" in r.output

    def test_changed_outside_repo_is_loud(self, tmp_path, monkeypatch):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("GIT_CEILING_DIRECTORIES", str(tmp_path))
        r = CliRunner().invoke(cli, ["lint", "--changed"])
        assert r.exit_code == 2


# -- lock-contention profiler -----------------------------------------------


@pytest.fixture
def lockprofile():
    from ray_tpu.devtools import lockdebug as mod
    mod.install_profile()
    try:
        yield mod
    finally:
        mod.uninstall_profile()
        mod.clear_contention()


class TestLockContentionProfile:
    def test_wait_and_hold_accounting(self, lockprofile):
        lock = threading.Lock()
        assert type(lock).__name__ == "_ProfileLock"

        # 64 uncontended pairs: hold timing samples 1-in-8 acquires.
        for _ in range(64):
            with lock:
                pass

        # One deterministic contended acquire: the worker parks on the
        # lock until the main thread releases it.
        parked = threading.Event()

        def worker():
            parked.set()
            with lock:
                pass

        lock.acquire()
        t = threading.Thread(target=worker)
        t.start()
        parked.wait(5.0)
        time.sleep(0.05)  # let the worker reach the blocked acquire
        lock.release()
        t.join(5.0)

        rep = lockprofile.contention_report()
        assert rep["installed"] is True
        row = next(r for r in rep["sites"]
                   if r["kind"] == "Lock" and r["acquires"] == 66)
        assert row["contended"] >= 1
        assert row["wait_max_s"] > 0.0
        assert row["wait_total_s"] >= row["wait_max_s"]
        # Histogram invariant: untimed zero-waits are backfilled into
        # bucket 0, so the buckets always sum to the acquire count.
        assert sum(row["wait_hist"]) == row["acquires"]
        assert row["hold_samples"] >= 8
        assert row["hold_mean_s"] >= 0.0
        assert row["hold_total_s"] >= 0.0
        assert len(row["wait_hist"]) == len(rep["bucket_bounds_s"]) + 1
        json.dumps(rep)  # bundle-serializable

        text = lockprofile.format_contention(rep)
        assert row["site"] in text

    def test_rlock_reentrancy_counts_outermost_only(self, lockprofile):
        r = threading.RLock()
        with r:
            with r:
                pass
        rep = lockprofile.contention_report()
        row = next(x for x in rep["sites"] if x["kind"] == "RLock"
                   and x["site"] == r.site)
        assert row["acquires"] == 1
        assert row["contended"] == 0

    def test_condition_on_profiled_lock_works(self, lockprofile):
        cond = threading.Condition()
        hit = []

        def waiter():
            with cond:
                hit.append(cond.wait(timeout=5.0))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(5.0)
        assert hit == [True]

    def test_contention_reaches_debug_bundle(self, lockprofile, tmp_path):
        lock = threading.Lock()
        with lock:
            pass

        from ray_tpu._private.diagnostics import write_debug_bundle

        class _Rt:
            session_dir = str(tmp_path)
        path = write_debug_bundle(_Rt(), "contention_test",
                                  capture_stacks=False)
        with open(os.path.join(path, "lock_contention.json")) as f:
            doc = json.load(f)
        assert doc["installed"] is True
        assert any(r["acquires"] >= 1 for r in doc["sites"])
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        assert "lock_contention.json" in manifest["contents"]

    def test_lock_report_cli_renders_bundle_file(self, lockprofile,
                                                 tmp_path):
        lock = threading.Lock()
        for _ in range(16):
            with lock:
                pass
        rep = lockprofile.contention_report()
        f = tmp_path / "lock_contention.json"
        f.write_text(json.dumps(rep))

        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        r = CliRunner().invoke(cli, ["lint", "--lock-report", str(f)])
        assert r.exit_code == 0
        assert lock.site in r.output

        r = CliRunner().invoke(cli, ["lint", "--lock-report",
                                     str(tmp_path / "nope.json")])
        assert r.exit_code == 2

    def test_debug_mode_also_collects_contention(self):
        from ray_tpu.devtools import lockdebug as mod
        mod.install()
        try:
            lock = threading.Lock()
            with lock:
                pass
            rep = mod.contention_report()
            assert rep["installed"] is True
            assert any(r["acquires"] >= 1 for r in rep["sites"])
        finally:
            mod.uninstall()
            mod.clear()
