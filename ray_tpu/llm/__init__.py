"""ray_tpu.llm — TPU-native LLM serving and batch inference.

Reference analog: python/ray/llm/ (vLLM-backed serve + batch,
llm/_internal/serve/engines/vllm/, batch/stages/vllm_engine_stage.py).
The reference delegates the engine to vLLM (CUDA); here the engine is
JAX-native: paged KV cache laid out for the TPU paged-attention kernel,
jit-compiled continuous-batching decode over all active slots, and
length-bucketed prefill — served either as a serve deployment
(``build_llm_deployment``) or driven directly for offline batch
inference (``InferenceEngine.generate``).
"""

from ._cache import PagePool
from .engine import InferenceEngine, Request, SamplingParams
from .serving import LLMServer, build_llm_deployment

__all__ = [
    "InferenceEngine", "SamplingParams", "Request", "PagePool",
    "LLMServer", "build_llm_deployment",
    # Disaggregated serving (prefill/decode split + SLO router) lives in
    # ray_tpu.llm.disagg; the multi-replica decode fleet (prefix-affinity
    # routing + replica autoscaling) in ray_tpu.llm.fleet; both imported
    # lazily to keep bare engine imports light.
    "disagg",
    "fleet",
]


def __getattr__(name):
    if name in ("disagg", "fleet"):
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
