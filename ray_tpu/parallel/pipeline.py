"""Microbatched pipeline parallelism over the ``pp`` mesh axis.

The reference has no in-repo pipeline engine — it delegates PP to vLLM
(reference: llm/_internal/common/placement.py:47 sizes PG bundles as TP*PP)
or hands users the compiled-graph substrate to build their own (reference:
python/ray/dag/compiled_dag_node.py:804).  Here PP is a first-class GSPMD
strategy: transformer blocks are stacked [L, ...] and sharded over ``pp``
on the layer axis (each device keeps L/pp resident stage layers), and a
``shard_map`` island — manual only over ``pp``, all other mesh axes stay in
GSPMD auto mode — runs the GPipe schedule: at each of M + pp - 1 steps every
stage processes one microbatch and hands its activation to the next stage
with a single ICI hop (``lax.ppermute``).  Autodiff through the scan +
ppermute yields the reverse schedule for backward automatically.

Pipeline-bubble cost is the standard M/(M + pp - 1) utilization; raise
``num_microbatches`` to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from .mesh import AXIS_PIPELINE, get_global_mesh


def _pipeline_island(stage_params, x_mb, *, stage_body, axis_name: str,
                     num_stages: int, num_microbatches: int):
    """Runs inside shard_map: stage_params is this stage's [L/pp, ...]
    slice; x_mb is the full [M, mb, S, E] microbatched input (replicated
    over pp)."""
    stage = jax.lax.axis_index(axis_name)
    M = num_microbatches
    steps = M + num_stages - 1

    def step(buf, t):
        # Stage 0 feeds microbatch t (clipped; bubble steps recompute the
        # last microbatch and their output is never consumed), other stages
        # consume what the previous stage handed over.
        mb_idx = jnp.clip(t, 0, M - 1)
        x_t = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(stage == 0, x_t.astype(buf.dtype), buf)
        y = stage_body(stage_params, inp)
        # Hand to the next stage (i -> i+1); stage 0 receives zeros.
        perm = [(i, i + 1) for i in range(num_stages - 1)]
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return nxt, y

    buf0 = jnp.zeros_like(x_mb[0])
    _, ys = jax.lax.scan(step, buf0, jnp.arange(steps))
    # Microbatch m leaves the last stage at step m + num_stages - 1.
    outs = ys[num_stages - 1:]
    # Broadcast the last stage's (only real) outputs to every pp rank so
    # the replicated lm_head/loss after the island sees correct values.
    mask = (stage == num_stages - 1).astype(outs.dtype)
    return jax.lax.psum(outs * mask, axis_name)


def pipeline_blocks(stacked_params, x, stage_body: Callable, *,
                    num_microbatches: int, mesh=None,
                    axis_name: str = AXIS_PIPELINE):
    """Run stacked transformer blocks as a microbatched pipeline.

    stacked_params: pytree with leading layer axis [L, ...], sharded over
        ``axis_name`` (the "layers" logical axis mapped to pp).
    x: [B, S, E] activations; B must divide by num_microbatches.
    stage_body(stage_params, h) -> h: applies one stage's layers.

    Returns [B, S, E].
    """
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = get_global_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError(f"pipeline_blocks needs a mesh with a "
                         f"{axis_name!r} axis")
    num_stages = mesh.shape[axis_name]
    B, S, E = x.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    n_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_layers % num_stages:
        raise ValueError(
            f"layers ({n_layers}) must divide evenly over pp stages "
            f"({num_stages})")

    x_mb = x.reshape(M, B // M, S, E)
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)

    body = partial(_pipeline_island, stage_body=stage_body,
                   axis_name=axis_name, num_stages=num_stages,
                   num_microbatches=M)
    if hasattr(jax, "shard_map"):
        island = jax.shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            axis_names={axis_name},  # manual over pp only; rest GSPMD
            check_vma=False,
        )
    else:
        # Pre-stable API (jax < 0.6): always take the fully-manual
        # lowering (empty auto set) — partial-auto lowers a PartitionId
        # op legacy XLA-CPU cannot partition.  The in/out specs claim
        # every non-pp axis replicated, so shard_map all-gathers the
        # batch/params onto each rank and the pp psum-broadcast output
        # is truly replicated: numerically identical to
        # manual-over-pp-only, at an activation-memory cost acceptable
        # for the legacy fallback.
        from jax.experimental.shard_map import shard_map as _shard_map
        island = _shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            check_rep=False,
        )
    out = island(stacked_params, x_mb)
    return out.reshape(B, S, E)
