"""Model + SPMD train-step tests on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import (LlamaConfig, forward, init_params, llama_tiny,
                            loss_fn, param_logical_axes)
from ray_tpu.models.llama import num_params
from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.parallel.spmd import make_lm_train_step


def _batch(cfg, B=2, S=64, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    return {"tokens": jnp.asarray(toks)}


class TestLlamaForward:
    def test_shapes_and_finite(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        params = init_params(cfg, jax.random.key(0))
        logits = forward(params, _batch(cfg)["tokens"], cfg)
        assert logits.shape == (2, 64, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        params = init_params(cfg, jax.random.key(0))
        t1 = _batch(cfg, B=1)["tokens"]
        t2 = t1.at[0, 50].set((t1[0, 50] + 1) % cfg.vocab_size)
        l1 = forward(params, t1, cfg)
        l2 = forward(params, t2, cfg)
        np.testing.assert_allclose(np.asarray(l1[0, :50]),
                                   np.asarray(l2[0, :50]), atol=1e-5)
        assert not np.allclose(l1[0, 50:], l2[0, 50:], atol=1e-5)

    def test_loss_decreases_under_sgd(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        params = init_params(cfg, jax.random.key(0))
        batch = _batch(cfg)
        g = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, batch, cfg)))
        l0, grads = g(params)
        params2 = jax.tree.map(lambda p, d: p - 0.5 * d, params, grads)
        l1, _ = g(params2)
        assert l1 < l0

    def test_num_params_matches(self):
        cfg = llama_tiny()
        params = init_params(cfg, jax.random.key(0))
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert total == num_params(cfg)

    def test_moe_variant(self):
        cfg = llama_tiny().replace(num_experts=4, dtype=jnp.float32,
                                   remat=False)
        params = init_params(cfg, jax.random.key(0))
        loss = loss_fn(params, _batch(cfg), cfg)
        assert np.isfinite(loss)

    def test_logical_axes_tree_matches_params(self):
        cfg = llama_tiny().replace(num_experts=4)
        params = init_params(cfg, jax.random.key(0))
        logical = param_logical_axes(cfg)
        ps = jax.tree.structure(params)
        ls = jax.tree.structure(
            logical, is_leaf=lambda x: isinstance(x, tuple))
        assert ps == ls
        for p, ax in zip(
                jax.tree.leaves(params),
                jax.tree.leaves(logical,
                                is_leaf=lambda x: isinstance(x, tuple))):
            assert p.ndim == len(ax)


class TestShardedTrainStep:
    def _run_steps(self, mesh_spec, cfg, n=3, B=8, S=64, devices=None):
        mesh = build_mesh(mesh_spec, devices=devices)
        init_fn, step_fn, place = make_lm_train_step(
            cfg, mesh, learning_rate=1e-2)
        params, opt = init_fn(jax.random.key(0))
        losses = []
        for i in range(n):
            batch = place(_batch(cfg, B=B, S=S, seed=i % 2))
            params, opt, metrics = step_fn(params, opt, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        return losses

    def test_dp_only(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        losses = self._run_steps(MeshSpec(dp=8), cfg)
        assert losses[-1] < losses[0]

    def test_fsdp_tp(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        losses = self._run_steps(MeshSpec(dp=2, fsdp=2, tp=2), cfg)
        assert losses[-1] < losses[0]

    def test_ring_sp(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False,
                                   attention_impl="ring")
        losses = self._run_steps(MeshSpec(dp=2, sp=4), cfg, B=4, S=64)
        assert losses[-1] < losses[0]

    def test_ulysses_sp(self):
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False,
                                   attention_impl="ulysses")
        losses = self._run_steps(MeshSpec(dp=2, sp=2, tp=2), cfg, B=4, S=64)
        assert losses[-1] < losses[0]

    def test_moe_ep(self):
        cfg = llama_tiny().replace(num_experts=4, dtype=jnp.float32,
                                   remat=False)
        losses = self._run_steps(MeshSpec(dp=2, ep=4), cfg)
        assert losses[-1] < losses[0]

    def test_multi_slice_hybrid_mesh(self):
        """Multi-slice (DCN) training: dp split across 2 slices with tp
        inside each (reference: MEGASCALE multi-slice world + hybrid
        device mesh; dp outermost so gradient allreduce rides DCN)."""
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        losses = self._run_steps(MeshSpec(dp=4, tp=2, num_slices=2), cfg)
        assert losses[-1] < losses[0]
        # Multi-slice must compute the same numbers as the flat mesh.
        l_flat = self._run_steps(MeshSpec(dp=4, tp=2), cfg, n=2)
        l_ms = self._run_steps(MeshSpec(dp=4, tp=2, num_slices=2), cfg, n=2)
        np.testing.assert_allclose(l_ms, l_flat, rtol=2e-4)

    def test_sharded_matches_single_device(self):
        """The 8-way sharded step must compute the same loss as 1 device."""
        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False)
        l_sharded = self._run_steps(MeshSpec(dp=2, fsdp=2, tp=2), cfg, n=2)
        l_single = self._run_steps(MeshSpec(), cfg, n=2,
                                   devices=jax.devices()[:1])
        np.testing.assert_allclose(l_sharded, l_single, rtol=2e-4)

    def test_ring_matches_dense(self):
        cfg_ring = llama_tiny().replace(dtype=jnp.float32, remat=False,
                                        attention_impl="ring")
        cfg_ref = llama_tiny().replace(dtype=jnp.float32, remat=False)
        l_ring = self._run_steps(MeshSpec(dp=2, sp=4), cfg_ring, n=2, B=8)
        l_ref = self._run_steps(MeshSpec(dp=8), cfg_ref, n=2, B=8)
        np.testing.assert_allclose(l_ring, l_ref, rtol=2e-4)


class TestTrainStepOptions:
    def _setup(self, **cfg_kw):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.llama import llama_tiny
        from ray_tpu.parallel import MeshSpec, build_mesh
        from ray_tpu.parallel.spmd import make_lm_train_step

        cfg = llama_tiny().replace(dtype=jnp.float32, remat=False,
                                   attention_impl="reference", **cfg_kw)
        mesh = build_mesh(MeshSpec(dp=-1))   # all (virtual) devices
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 512, (8, 256))
        mask = np.ones((8, 256), np.float32)
        mask[:2, 10:] = 0.0          # uneven masking across microbatches
        batch = {"tokens": jnp.asarray(tokens, jnp.int32),
                 "loss_mask": jnp.asarray(mask)}
        return cfg, mesh, batch

    def _run(self, cfg, mesh, batch, **step_kw):
        import jax

        from ray_tpu.parallel.spmd import make_lm_train_step

        init_fn, step_fn, place = make_lm_train_step(
            cfg, mesh, learning_rate=1e-3, **step_kw)
        params, opt = init_fn(jax.random.key(0))
        for _ in range(3):
            params, opt, m = step_fn(params, opt, place(dict(batch)))
        return float(m["loss"]), float(m["grad_norm"])

    def test_grad_accum_matches_single_step(self):
        """grad_accum is a pure memory trade: losses and grads equal the
        unaccumulated step exactly, including uneven loss masking (every
        microbatch normalizes by the FULL batch's token count)."""
        cfg, mesh, batch = self._setup()
        l1, g1 = self._run(cfg, mesh, batch)
        l3, g3 = self._run(cfg, mesh, batch, grad_accum=4)
        assert abs(l1 - l3) < 1e-4
        assert abs(g1 - g3) / g1 < 1e-3

    def test_chunked_ce_matches_fused(self):
        """loss_chunks computes the lm_head in sequence chunks under
        remat: same loss and grads as the fused logits path, with only
        one chunk's f32 logits ever resident."""
        cfg, mesh, batch = self._setup()
        l0, g0 = self._run(cfg, mesh, batch)
        cfg8, _, _ = self._setup(loss_chunks=8)
        l8, g8 = self._run(cfg8, mesh, batch)
        assert abs(l0 - l8) < 1e-4
        assert abs(g0 - g8) / g0 < 1e-3
