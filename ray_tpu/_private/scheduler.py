"""Cluster scheduler: dependency resolution, policies, placement groups.

Maps the reference's two-level lease scheduler (reference:
src/ray/raylet/scheduling/cluster_lease_manager.h:41 queueing + node
selection, local_lease_manager.h:61 local dispatch, policies under
raylet/scheduling/policy/ — hybrid_scheduling_policy.cc pack-then-spread,
spread, node-affinity, bundle_scheduling_policy.cc) into one in-process
component: tasks enter a dependency stage (reference:
lease_dependency_manager.h), move to a ready queue, a policy picks a node,
resources are pinned, and the node's worker pool gets a dispatch callback.

TPU-first addition: resources are typed (``TPU`` chips, ``TPU-<gen>-head``
slice markers) and placement-group bundles model pod slices, so gang
placement of an SPMD worker group = one STRICT_SPREAD slice PG (the
SlicePlacementGroup concept, reference: python/ray/util/tpu.py:414, moved
into the scheduler proper).
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .config import Config
from .controller import (Controller, NodeInfo, PlacementGroupInfo, PG_CREATED,
                         PG_PENDING, PG_REMOVED)
from .ids import NodeID, ObjectID, PlacementGroupID, TaskID
from .protocol import TaskSpec
from .resources import ResourceSet
from ..util import telemetry

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: "NodeID"
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object  # PlacementGroup handle or PlacementGroupID
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class _PendingTask:
    spec: TaskSpec
    unresolved: Set[ObjectID]
    dispatch: Callable[[TaskSpec, NodeID], None]
    key: Any = None  # scheduling-class key (computed once at submit)


@dataclass
class _NodeState:
    info: NodeInfo
    available: ResourceSet
    # Per-PG-bundle reserved-and-still-free resources.
    bundle_available: Dict[Tuple[PlacementGroupID, int], ResourceSet] = field(
        default_factory=dict)


class Infeasible(Exception):
    """No alive node could ever satisfy the request."""


class ClusterScheduler:
    def __init__(self, controller: Controller,
                 object_ready: Callable[[ObjectID], bool]):
        self._controller = controller
        self._object_ready = object_ready
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, _NodeState] = {}
        # Ready tasks bucketed by scheduling class (reference: SchedulingKey
        # grouping in normal_task_submitter.h): each wake visits classes,
        # not tasks, so a full queue behind exhausted resources costs
        # O(classes) per pass instead of O(tasks).
        self._ready: "dict[Any, deque]" = {}
        self._ready_count = 0
        self._waiting: Dict[ObjectID, List[_PendingTask]] = defaultdict(list)
        self._infeasible: List[_PendingTask] = []
        # Draining nodes (preemption notice): unschedulable for NEW
        # leases/bundles; tasks already running there finish or evacuate.
        self._draining: Set[NodeID] = set()
        self._wake = threading.Condition(self._lock)
        self._running = True
        self._spread_rr = 0
        self._pending_pgs: List[PlacementGroupInfo] = []
        # Set by the Runtime: called with (spec, exc) when dispatch blows up.
        self.on_dispatch_error: Optional[Callable] = None
        # Set by the Runtime: called with (spec) when the cluster is full;
        # returns True if the task was queued ahead on a busy worker
        # (pipelined submission, reference: max_tasks_in_flight_per_worker
        # in the C++ submitter) — such tasks hold NO resource booking.
        self.try_pipeline: Optional[Callable] = None
        self._thread = threading.Thread(target=self._loop, name="scheduler",
                                        daemon=True)
        self._thread.start()

    # -- node lifecycle -----------------------------------------------------

    def add_node(self, info: NodeInfo) -> None:
        with self._wake:
            self._nodes[info.node_id] = _NodeState(info, info.total_resources.copy())
            # Newly added capacity may unblock infeasible tasks.
            for t in self._infeasible:
                self._push_ready_locked(t)
            self._infeasible.clear()
            self._wake.notify_all()

    def remove_node(self, node_id: NodeID) -> None:
        with self._wake:
            self._nodes.pop(node_id, None)
            self._draining.discard(node_id)
            self._wake.notify_all()

    def set_draining(self, node_id: NodeID, draining: bool) -> None:
        """Fence a node off from new placements (drain notice), or lift
        the fence.  Existing bookings/bundles on the node are untouched —
        work already there drains through its own lifecycle."""
        with self._wake:
            if draining:
                self._draining.add(node_id)
            else:
                self._draining.discard(node_id)
                # Capacity became visible again: queued tasks may now fit.
                self._wake.notify_all()

    def available_resources(self) -> Dict[str, float]:
        """Schedulable capacity: draining nodes are excluded — their
        resources are about to vanish, and counting them would make
        elastic policies / the autoscaler size work onto a doomed host."""
        with self._lock:
            total = ResourceSet()
            for ns in self._nodes.values():
                if ns.info.node_id in self._draining:
                    continue
                total = total + ns.available
            return total.to_dict()

    def total_resources(self) -> Dict[str, float]:
        with self._lock:
            total = ResourceSet()
            for ns in self._nodes.values():
                total = total + ns.info.total_resources
            return total.to_dict()

    # -- task intake --------------------------------------------------------

    def submit(self, spec: TaskSpec,
               dispatch: Callable[[TaskSpec, NodeID], None]) -> None:
        deps = {a[1] for a in spec.arg_descs if a[0] == "ref"}
        deps |= {d[1] for d in spec.kwarg_descs.values() if d[0] == "ref"}
        # Readiness must be checked under the scheduler lock: an object can
        # become ready between the check and registration, and
        # notify_object_ready (which holds the same lock) would then have
        # already fired, stranding the task in _waiting forever.
        inline_node: Optional[NodeID] = None
        pipeline_ok = False
        with self._wake:
            unresolved = {d for d in deps if not self._object_ready(d)}
            if not unresolved and not self._ready_count \
                    and not self._pending_pgs:
                # Submit-time fast path: with an empty queue, place and
                # book right here and dispatch on the caller's thread —
                # no scheduler-loop wakeup, no GIL handoff per task
                # (reference: normal_task_submitter.cc:142 pipelines
                # lease grants the same way).
                inline_node = self._try_place(spec)
                if inline_node is None and self.try_pipeline is not None \
                        and self._pipelineable(spec):
                    pipeline_ok = True  # attempt outside the lock
            if inline_node is None and not pipeline_ok:
                self._queue_task_locked(spec, dispatch, unresolved)
        if inline_node is not None:
            self._dispatch_safely(spec, dispatch, inline_node)
        elif pipeline_ok:
            if not self.try_pipeline(spec):
                with self._wake:
                    self._queue_task_locked(spec, dispatch, set())

    def take_pipelineable(self) -> Optional[_PendingTask]:
        """Pop a queued task eligible for pipelined dispatch (a pipelined
        completion freed a worker queue slot)."""
        with self._wake:
            if not self._running:
                return None
            for key in list(self._ready):
                bucket = self._ready[key]
                t = bucket[0]
                if self._pipelineable(t.spec):
                    bucket.popleft()
                    self._ready_count -= 1
                    if not bucket:
                        self._ready.pop(key, None)
                    return t
            return None

    @staticmethod
    def _pipelineable(spec: TaskSpec) -> bool:
        """Plain CPU-only tasks can queue ahead on a busy worker: execution
        stays serial per worker, so actual parallelism remains bounded by
        the booked capacity."""
        return (spec.placement_group is None
                and spec.scheduling_strategy is None
                and spec.runtime_env is None
                and spec.actor_id is None and spec.create_actor_id is None
                and all(k == "CPU" for k in spec.resources.keys()))

    def _queue_task_locked(self, spec: TaskSpec, dispatch,
                           unresolved: Set[ObjectID]) -> None:
        task = _PendingTask(spec, unresolved, dispatch,
                            self._sched_key(spec))
        if unresolved:
            for d in unresolved:
                self._waiting[d].append(task)
        else:
            self._push_ready_locked(task)
            # Wake the loop only when the task has a chance of placing
            # right now: with every worker busy, the wakeup is a pure GIL
            # handoff per submit (measured ~100us each at 2k submits/s)
            # and release() will wake the loop anyway when capacity frees.
            # Both paths hold this lock, so the check-then-notify cannot
            # miss a concurrent release.
            if self._capacity_hint(spec):
                self._wake.notify_all()

    def _dispatch_safely(self, spec: TaskSpec, dispatch, node_id: NodeID):
        try:
            dispatch(spec, node_id)
        except Exception as exc:
            # Undo the resource deduction and surface the error; silently
            # dropping would leak capacity and hang get().
            self.release(node_id, spec.resources, spec.placement_group,
                         spec.bundle_index)
            if self.on_dispatch_error is not None:
                try:
                    self.on_dispatch_error(spec, exc)
                except Exception as e:
                    telemetry.note_swallowed("scheduler.on_dispatch_error", e)

    def exchange_finished(self, node_id: NodeID,
                          spec: TaskSpec) -> Optional[_PendingTask]:
        """A task of ``spec``'s scheduling class just finished on
        ``node_id``: transfer its resource booking to a queued task of the
        SAME class and return it for immediate dispatch (lease reuse,
        reference: normal-task lease pipelining) — or release the booking
        and return None.  Caller restricts this to plain tasks (no PG, no
        TPU grant, no runtime_env)."""
        key = self._sched_key(spec)
        with self._wake:
            # Reuse only while this class is the ONLY queued class and the
            # scheduler is live: with other classes waiting, release and
            # let the loop's FIFO-over-classes scan arbitrate — an endless
            # same-class stream must not starve earlier-queued classes.
            bucket = self._ready.get(key)
            if bucket and self._running and len(self._ready) == 1 \
                    and not self._pending_pgs:
                task = bucket.popleft()
                self._ready_count -= 1
                if not bucket:
                    self._ready.pop(key, None)
                return task
        self.release(node_id, spec.resources)
        return None

    def _capacity_hint(self, spec: TaskSpec) -> bool:
        """Cheap may-fit check (false negatives are latency-free thanks to
        release()'s notify; when unsure, say yes)."""
        need = spec.resources
        if spec.placement_group is not None:
            return True
        for ns in self._nodes.values():
            if ns.info.node_id not in self._draining and \
                    need.fits(ns.available):
                return True
        return False

    def _push_ready_locked(self, task: _PendingTask) -> None:
        if task.key is None:
            task.key = self._sched_key(task.spec)
        self._ready.setdefault(task.key, deque()).append(task)
        self._ready_count += 1

    def notify_object_ready(self, object_id: ObjectID) -> None:
        with self._wake:
            tasks = self._waiting.pop(object_id, [])
            moved = False
            for t in tasks:
                t.unresolved.discard(object_id)
                if not t.unresolved:
                    self._push_ready_locked(t)
                    moved = True
            if moved:
                self._wake.notify_all()

    def release(self, node_id: NodeID, resources: ResourceSet,
                pg: Optional[PlacementGroupID] = None,
                bundle_index: int = -1) -> None:
        with self._wake:
            ns = self._nodes.get(node_id)
            if ns is None:
                return
            if pg is not None:
                key = (pg, bundle_index) if bundle_index >= 0 else None
                if key is not None and key in ns.bundle_available:
                    ns.bundle_available[key] = ns.bundle_available[key] + resources
                else:
                    # PG was removed while the task ran: resources go back to
                    # the node's main pool.
                    ns.available = ns.available + resources
            else:
                ns.available = ns.available + resources
            self._wake.notify_all()

    # -- scheduling loop ----------------------------------------------------

    @staticmethod
    def _sched_key(spec: TaskSpec):
        """Scheduling-class key (reference: SchedulingKey in
        normal_task_submitter.h): tasks with identical resource shape,
        placement target and strategy place identically, so one failed
        placement disqualifies the whole class for this round — turning the
        O(queue) rescan per wake into O(distinct classes)."""
        res = tuple(sorted(spec.resources.to_dict().items()))
        strat = spec.scheduling_strategy
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            strat = ("affinity", strat.node_id, strat.soft)
        return (res, spec.placement_group, spec.bundle_index, strat)

    def _loop(self) -> None:
        while True:
            # Phase 1 (locked): pick placements and deduct resources.
            # Phase 2 (unlocked): run the dispatches — arg resolution,
            # spec pickling and the worker-pipe send are the expensive
            # part, and holding the condvar through them would serialize
            # every submit/release/notify in the system behind each
            # dispatch (measured: ~770us average lock wait in the async
            # task microbenchmark before this split).
            to_dispatch = []
            with self._wake:
                while self._running and not self._ready_count:
                    self._retry_pending_pgs_locked()
                    self._wake.wait(timeout=0.5)
                if not self._running:
                    return
                self._retry_pending_pgs_locked()
                for key in list(self._ready):
                    bucket = self._ready.get(key)
                    while bucket:
                        task = bucket[0]
                        node_id = self._try_place(task.spec)
                        if node_id is None:
                            break  # whole class blocked this round
                        bucket.popleft()
                        self._ready_count -= 1
                        to_dispatch.append((task, node_id))
                    if not bucket:
                        self._ready.pop(key, None)
                if self._ready_count and not to_dispatch:
                    # Nothing placeable right now; sleep until resources
                    # free (release/notify wake us).
                    self._wake.wait(timeout=0.05)
            for task, node_id in to_dispatch:
                self._dispatch_safely(task.spec, task.dispatch, node_id)

    def stop(self) -> None:
        with self._wake:
            self._running = False
            self._wake.notify_all()

    # -- placement ----------------------------------------------------------

    def _bundle_key(self, ns: _NodeState, pg: PlacementGroupID,
                    bundle_index: int, need: ResourceSet):
        if bundle_index >= 0:
            key = (pg, bundle_index)
            return key if key in ns.bundle_available else None
        # Wildcard bundle: first bundle on this node with room.
        for key, avail in ns.bundle_available.items():
            if key[0] == pg and need.fits(avail):
                return key
        return None

    def _try_place(self, spec: TaskSpec) -> Optional[NodeID]:
        need = spec.resources
        if spec.placement_group is not None:
            for ns in self._nodes.values():
                key = self._bundle_key(ns, spec.placement_group,
                                       spec.bundle_index, need)
                if key is not None and need.fits(ns.bundle_available[key]):
                    ns.bundle_available[key] = ns.bundle_available[key] - need
                    return ns.info.node_id
            return None

        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            ns = self._nodes.get(strategy.node_id)
            if ns is not None and need.fits(ns.available) and \
                    strategy.node_id not in self._draining:
                ns.available = ns.available - need
                return ns.info.node_id
            if not strategy.soft:
                return None  # stays queued until that node frees up

        candidates = [ns for ns in self._nodes.values()
                      if ns.info.node_id not in self._draining
                      and need.fits(ns.available)]
        if not candidates:
            if not any(need.fits(ns.info.total_resources)
                       for ns in self._nodes.values()):
                pass  # infeasible now; capacity may still appear later
            return None

        if strategy == "SPREAD":
            self._spread_rr += 1
            ns = candidates[self._spread_rr % len(candidates)]
        else:
            ns = self._hybrid_pick(candidates)
        ns.available = ns.available - need
        return ns.info.node_id

    def _hybrid_pick(self, candidates: List[_NodeState]) -> _NodeState:
        """Pack onto busiest node under the threshold, else least utilized
        (reference: hybrid_scheduling_policy.cc)."""
        thresh = Config.get("scheduler_spread_threshold")

        def utilization(ns: _NodeState) -> float:
            utils = []
            for k, total in ns.info.total_resources.items():
                if total > 0:
                    utils.append(1.0 - ns.available.get(k) / total)
            return max(utils) if utils else 0.0

        under = [ns for ns in candidates if utilization(ns) < thresh]
        if under:
            return max(under, key=utilization)
        return min(candidates, key=utilization)

    # -- placement groups ---------------------------------------------------

    def create_placement_group(self, pg: PlacementGroupInfo) -> bool:
        """Two-phase reserve: compute full assignment against a snapshot,
        commit only if every bundle fits (reference:
        gcs_placement_group_scheduler.h:115 prepare/commit).  A group that
        does not fit yet stays PENDING and is retried whenever capacity
        frees up (reference: GcsPlacementGroupManager pending queue)."""
        with self._wake:
            if self._try_commit_pg(pg):
                return True
            self._pending_pgs.append(pg)
            return False

    def _try_commit_pg(self, pg: PlacementGroupInfo) -> bool:
        """Commit every still-unplaced bundle (all of them on first create;
        just the lost ones after a node death re-plan)."""
        pending = [b for b in pg.bundles if b.node_id is None]
        if not pending:
            self._controller.set_pg_state(pg.pg_id, PG_CREATED)
            return True
        # Draining nodes never receive NEW bundles (existing bundles on a
        # draining node stay committed; evacuation is the owner's call).
        snapshot = {nid: ns.available.copy()
                    for nid, ns in self._nodes.items()
                    if nid not in self._draining}
        used = {b.node_id for b in pg.bundles if b.node_id is not None}
        assignment = self._plan_bundles(pg, snapshot, pending, used)
        if assignment is None:
            return False
        for bundle, node_id in zip(pending, assignment):
            ns = self._nodes[node_id]
            ns.available = ns.available - bundle.resources
            ns.bundle_available[(pg.pg_id, bundle.index)] = bundle.resources.copy()
            bundle.node_id = node_id
        self._controller.set_pg_state(pg.pg_id, PG_CREATED)
        self._wake.notify_all()
        return True

    def reschedule_lost_bundles(self, pg: PlacementGroupInfo,
                                dead_node: NodeID) -> None:
        """Re-plan the bundles a dead node took with it; live bundles keep
        their placement (reference: GcsPlacementGroupManager rescheduling on
        node death)."""
        with self._wake:
            if pg.state == PG_REMOVED:
                return
            lost = False
            for b in pg.bundles:
                if b.node_id == dead_node:
                    b.node_id = None
                    lost = True
            if not lost:
                return
            self._controller.set_pg_state(pg.pg_id, PG_PENDING)
            if not self._try_commit_pg(pg) and pg not in self._pending_pgs:
                self._pending_pgs.append(pg)

    def _retry_pending_pgs_locked(self) -> None:
        if not self._pending_pgs:
            return
        still_pending = []
        for pg in self._pending_pgs:
            if pg.state == PG_REMOVED:
                continue
            if not self._try_commit_pg(pg):
                still_pending.append(pg)
        self._pending_pgs = still_pending

    def _plan_bundles(self, pg: PlacementGroupInfo,
                      snapshot: Dict[NodeID, ResourceSet],
                      bundles=None,
                      used_nodes: Optional[Set[NodeID]] = None
                      ) -> Optional[List[NodeID]]:
        bundles = pg.bundles if bundles is None else bundles
        node_ids = list(snapshot.keys())
        if not node_ids:
            return None
        assignment: List[NodeID] = []
        if pg.strategy == STRICT_PACK:
            # All bundles (incl. survivors) must share one node; a partial
            # re-plan must land on the surviving bundles' node if any.
            anchor = {b.node_id for b in pg.bundles if b.node_id is not None}
            cands = list(anchor) if anchor else node_ids
            for nid in cands:
                if nid not in snapshot:
                    continue
                avail = snapshot[nid].copy()
                ok = True
                for b in bundles:
                    if not b.resources.fits(avail):
                        ok = False
                        break
                    avail = avail - b.resources
                if ok:
                    return [nid] * len(bundles)
            return None
        used_nodes = set(used_nodes or ())
        order = node_ids if pg.strategy != SPREAD else random.sample(
            node_ids, len(node_ids))
        for b in bundles:
            placed = None
            if pg.strategy == STRICT_SPREAD:
                cands = [n for n in order if n not in used_nodes
                         and b.resources.fits(snapshot[n])]
            elif pg.strategy == SPREAD:
                cands = sorted(
                    (n for n in order if b.resources.fits(snapshot[n])),
                    key=lambda n: n in used_nodes)
            else:  # PACK: prefer already-used nodes
                cands = sorted(
                    (n for n in order if b.resources.fits(snapshot[n])),
                    key=lambda n: n not in used_nodes)
            if cands:
                placed = cands[0]
            if placed is None:
                return None
            snapshot[placed] = snapshot[placed] - b.resources
            used_nodes.add(placed)
            assignment.append(placed)
        return assignment

    def remove_placement_group(self, pg: PlacementGroupInfo) -> None:
        with self._wake:
            for b in pg.bundles:
                if b.node_id is None:
                    continue
                ns = self._nodes.get(b.node_id)
                if ns is None:
                    continue
                remaining = ns.bundle_available.pop((pg.pg_id, b.index), None)
                if remaining is not None:
                    # Return the whole bundle; in-use slices return via release().
                    ns.available = ns.available + remaining
                b.node_id = None
            self._controller.set_pg_state(pg.pg_id, PG_REMOVED)
            self._wake.notify_all()

    def num_pending(self) -> int:
        with self._lock:
            return self._ready_count + sum(
                len(v) for v in self._waiting.values())

    def pending_demand(self, include_pg_bundles: bool = True
                       ) -> List[Dict[str, float]]:
        """Unplaced resource shapes (one entry per queued task) — the
        autoscaler's demand feed (reference: GcsAutoscalerStateManager
        resource demand -> v2/scheduler.py bin-packing).

        ``include_pg_bundles=False`` leaves pending placement-group
        bundles out — gang-aware consumers take them atomically through
        ``pending_gang_demand`` instead."""
        with self._lock:
            out: List[Dict[str, float]] = []
            for bucket in self._ready.values():
                for t in bucket:
                    out.append(t.spec.resources.to_dict())
            for t in self._infeasible:
                out.append(t.spec.resources.to_dict())
            if not include_pg_bundles:
                return out
            pending_pg_shapes = []
            for pg in self._pending_pgs:
                for b in pg.bundles:
                    if b.node_id is None:
                        pending_pg_shapes.append(b.resources.to_dict())
            return out + pending_pg_shapes

    def pending_gang_demand(self) -> List[Tuple[str, List[Dict[str, float]],
                                                List]]:
        """Pending placement groups as atomic gangs: (strategy, [unplaced
        bundle shapes], [node_ids already holding this PG's bundles]) per
        pending PG.  A TPU slice reservation (SlicePlacementGroup ->
        STRICT_SPREAD PG) is exactly such a gang: the autoscaler must
        launch the whole multi-host node group or nothing, and spread
        bundles can never land on nodes the PG already occupies
        (reference: v2/scheduler.py:822 gang requests)."""
        with self._lock:
            out = []
            for pg in self._pending_pgs:
                shapes = [b.resources.to_dict() for b in pg.bundles
                          if b.node_id is None]
                placed = [b.node_id for b in pg.bundles
                          if b.node_id is not None]
                if shapes:
                    out.append((pg.strategy, shapes, placed))
            return out

    def per_node_available(self) -> Dict[NodeID, Dict[str, float]]:
        """Free resources per node (gang placement feasibility checks).
        Draining nodes are excluded — the drain fence and the
        autoscaler's gang launcher must agree: a doomed node's free
        capacity must never let a pending gang look placeable (the
        commit path would refuse it and the gang would wedge), nor
        suppress the whole-slice replacement buy."""
        with self._lock:
            return {nid: ns.available.to_dict()
                    for nid, ns in self._nodes.items()
                    if nid not in self._draining}
