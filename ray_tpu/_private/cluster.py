"""Multi-node cluster plane: head service, node join, object transfer.

The reference splits this across the GCS node manager (reference:
src/ray/gcs/gcs_node_manager.h — registration, liveness,
gcs_health_check_manager.h:46 probes), per-node raylets speaking gRPC
(src/ray/raylet/node_manager.cc:1798 lease protocol) and the object manager's
pull/push pair (src/ray/object_manager/pull_manager.h:50, push_manager.h:28)
with owner-based location lookup (ownership_object_directory.cc).

Here the head (driver) process stays the control plane — the round-1
Runtime/Controller/Scheduler — and grows a TCP listener that remote
``NodeServer`` processes join.  Each remote node runs the same
``NodeManager`` worker pool used locally, behind a small facade that
forwards runtime callbacks upstream.  The data plane is peer-to-peer: every
node (head included) runs a ``DataServer`` bound to its shm object store;
descriptors crossing node boundaries are tagged ``("at", node_id_bytes,
desc)`` and consumers pull the payload from the owner's data port, cache it
in their local store, and proceed zero-copy from there — the owner-directory
pattern with the head as the location oracle.

Transport: ``multiprocessing.connection`` over TCP with an HMAC authkey
(the cluster token).  Control messages are the dataclasses in protocol.py
plus the Up*/down wrappers below; object payloads ride the data plane, not
the control pipe.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque as _deque
from dataclasses import dataclass, field
from multiprocessing.connection import Client, Listener
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.storeview import events as _sv
from ray_tpu.util import telemetry, tracing

from . import sanitizer
from .config import Config
from .controller import NodeInfo
from .ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from .protocol import ContainedRefs as _ContainedRefs
from .protocol import (ActorStateMsg, BorrowRetained, GetReply, GetRequest,
                       PutFromWorker, RpcCall, RpcReply, TaskDone, TaskSpec,
                       WaitRequest)
from .resources import ResourceSet

# NOTE: the control/data listeners authenticate with an HMAC token and then
# unpickle peer messages — treat the token as a secret.  There is no silent
# well-known default: the head generates a random token when none is given
# (see Runtime.__init__) and joiners must present it.
DEFAULT_TOKEN = b"ray-tpu-cluster"  # explicit opt-in only (tests/demos)


# --------------------------------------------------------------------------
# wire messages (head <-> node server)
# --------------------------------------------------------------------------

@dataclass
class RegisterNode:
    hostname: str
    resources: Dict[str, float]
    num_tpu_chips: int
    data_address: Tuple[str, int]
    os_pid: int = 0
    # Set on reconnect after a dropped control connection: the node asks
    # to re-attach under its existing identity, keeping workers/tasks
    # alive (reference: raylets re-attaching after GCS failover;
    # retryable_grpc_client.h reconnect semantics).  last_down_seq tells
    # the head which down-messages arrived so it resends exactly the lost
    # tail (sequence-numbered redelivery).
    rejoin_node_id: Optional[bytes] = None
    last_down_seq: int = 0


@dataclass
class RegisterClient:
    """Remote-driver handshake (reference: python/ray/util/client/ —
    ray client connecting to the cluster's client server)."""
    hostname: str
    os_pid: int = 0


@dataclass
class ClientAck:
    client_id_bytes: bytes
    job_id_bytes: bytes
    config_blob: str
    head_node_id_bytes: bytes


@dataclass
class RegisterAck:
    node_id_bytes: bytes
    job_id_bytes: bytes
    config_blob: str
    head_data_address: Tuple[str, int]
    head_node_id_bytes: bytes
    # Highest up-message sequence the head processed from this node (the
    # node resends everything after it on re-attach).
    last_up_seq: int = 0
    # True when a WAL-restarted head accepted this re-attach: the head
    # lost all in-memory state, so the node must reset its down-seq
    # tracking and kill actor workers the new head knows nothing about
    # (revived actors re-create elsewhere).
    wal_resumed: bool = False


@dataclass
class DispatchTask:
    spec: TaskSpec
    args: list
    kwargs: dict
    target_worker: Optional[WorkerID]
    # Pipelined (lease-less) dispatch: queue ahead on a busy pooled worker
    # instead of granting a booked lease; the node answers with
    # UpPipelineReject when no worker has pipeline room (reference: the
    # C++ submitter's max_tasks_in_flight_per_worker pipelining,
    # normal_task_submitter.cc:516).
    pipelined: bool = False


@dataclass
class ToWorker:
    worker_id: WorkerID
    msg: Any


@dataclass
class KillActorWorker:
    worker_id: WorkerID
    force: bool = True


@dataclass
class NodeShutdown:
    pass


@dataclass
class FreeObject:
    """Head -> owner node: delete a GC'd object from the local store."""
    desc: tuple


@dataclass
class Ping:
    t: float


@dataclass
class Pong:
    t: float


@dataclass
class NodeRpc:
    """Node server -> head control call (same ctl_* registry as workers)."""
    request_id: int
    method: str
    args: tuple
    kwargs: dict


@dataclass
class NodeRpcReply:
    request_id: int
    value: Any
    error: Optional[str] = None


# Upstream runtime callbacks (node server -> head), mirroring the method
# calls NodeManager makes on the driver Runtime.
@dataclass
class UpTaskDone:
    msg: TaskDone


@dataclass
class UpNoteTaskRunning:
    task_id: TaskID
    worker_id: WorkerID


@dataclass
class UpWorkerDied:
    worker_id: WorkerID
    running: List[TaskID]
    actor_id: Optional[ActorID]
    reason: str = ""


@dataclass
class UpSyncView:
    """Node -> head versioned resource/load view (reference:
    src/ray/ray_syncer/ray_syncer.h:91 — ResourceViewSyncMessage broadcast;
    sent only when the view changes, with a monotonically increasing
    version so stale messages are dropped on receipt)."""
    version: int
    view: Dict[str, Any]


@dataclass
class UpDispatchFailed:
    spec: TaskSpec
    reason: str
    lost_object_bytes: Optional[bytes] = None


@dataclass
class UpPipelineReject:
    """Node -> head: a pipelined dispatch found no worker with queue room;
    the head returns the task's credit and resubmits through normal
    (booked) scheduling."""
    spec: TaskSpec


@dataclass
class UpFailTask:
    """Task failed before leaving the node (e.g. its wire frame could not
    serialize); only the raw ids are known."""
    task_id_bytes: bytes
    return_id_bytes: List[bytes]
    reason: str


@dataclass
class UpReleaseResources:
    resources: Dict[str, float]
    pg_bytes: Optional[bytes]
    bundle_index: int


@dataclass
class UpBindActor:
    actor_id: ActorID
    worker_id: WorkerID


@dataclass
class UpSubmit:
    spec: TaskSpec


@dataclass
class UpActorState:
    msg: ActorStateMsg
    worker_id: WorkerID


@dataclass
class StackDumpAll:
    """head -> node server: forward a StackDumpRequest to every live
    worker on the node (cluster half of ``ctl_stack_dump``)."""
    dump_id: int


@dataclass
class UpStackReply:
    """node server -> head: one worker's StackDumpReply."""
    msg: Any  # protocol.StackDumpReply


@dataclass
class UpStackExpect:
    """node server -> head: the worker set a StackDumpAll was fanned out
    to — lets the head account a wedged REMOTE worker as unresponsive
    instead of silently omitting it from the dump."""
    dump_id: int
    worker_ids: List[WorkerID]


@dataclass
class ProfileAll:
    """head -> node server: forward a ProfileRequest to every live
    worker on the node (cluster half of ``ctl_profile``)."""
    req: Any  # protocol.ProfileRequest


@dataclass
class UpProfileReply:
    """node server -> head: one worker's ProfileReply."""
    msg: Any  # protocol.ProfileReply


@dataclass
class UpProfileExpect:
    """node server -> head: the worker set a ProfileAll fanned out to
    (mirror of UpStackExpect — a remote worker that never replies is
    reported as unresponsive, not silently missing)."""
    profile_id: int
    worker_ids: List[WorkerID]


# --------------------------------------------------------------------------
# descriptor location tagging
# --------------------------------------------------------------------------

def tag_desc(desc, node_id_bytes: bytes):
    """Mark a node-local descriptor with its owner node."""
    if isinstance(desc, tuple) and desc and desc[0] in ("shm", "shma"):
        return ("at", node_id_bytes, desc)
    return desc


def untag_desc(desc, local_node_id_bytes: bytes):
    """Strip an "at" tag when the object is local; else return None."""
    if isinstance(desc, tuple) and desc and desc[0] == "at":
        if desc[1] == local_node_id_bytes:
            return desc[2]
        return None
    return desc


def desc_key(desc) -> Optional[bytes]:
    """Stable fetch key for a (possibly inner) descriptor."""
    if desc[0] == "shma":
        return desc[4]
    if desc[0] == "shm":
        return desc[1].encode()
    return None


def desc_object_id(desc) -> Optional[ObjectID]:
    """Recover the ObjectID a store descriptor names (shma embeds the id;
    shm segment names are rt_<hex>)."""
    try:
        if desc[0] == "shma":
            return ObjectID(desc[4])
        if desc[0] == "shm":
            return ObjectID(bytes.fromhex(desc[1].split("_", 1)[1]))
    except (ValueError, IndexError):
        return None
    return None


# --------------------------------------------------------------------------
# data plane: per-node object server + pull client
# --------------------------------------------------------------------------

def _drain_acceptor(listener, thread) -> None:
    """Unblock a thread sitting in ``listener.accept()`` and join it BEFORE
    closing the listener: closing the fd under a blocked accept lets the
    OS hand the fd number to a newer listener, whose handshakes the stale
    thread then steals and fails with its old authkey."""
    if thread is None or not thread.is_alive():
        return
    try:
        addr = listener.address
        s = socket.socket(socket.AF_INET)
        s.settimeout(1.0)
        host = addr[0] if addr[0] not in ("0.0.0.0", "") else "127.0.0.1"
        s.connect((host, addr[1]))
        s.close()
    except OSError:
        pass
    thread.join(timeout=3.0)


class DataServer:
    """Serves raw object payloads out of the local store (push side of the
    reference's PushManager, reference: push_manager.h:28 — one message per
    object; chunking is delegated to the socket layer)."""

    def __init__(self, store, token: bytes, host: str = "0.0.0.0",
                 advertise_host: str = "127.0.0.1"):
        self._store = store
        self._listener = Listener((host, 0), "AF_INET", authkey=token)
        # Advertised address must be peer-reachable (the bind host is a
        # wildcard); cross-machine clusters pass their routable IP.
        self.address: Tuple[str, int] = (advertise_host,
                                         self._listener.address[1])
        self._closed = False
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="data-server", daemon=True)
        self._acceptor.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn = self._listener.accept()
            except Exception:
                if self._closed:
                    return
                continue
            if self._closed:
                try:
                    conn.close()
                except Exception:
                    pass
                return
            sanitizer.spawn(self._serve, args=(conn,),
                            name="cluster-serve")

    def _serve(self, conn) -> None:
        try:
            while True:
                desc = conn.recv()
                t0 = time.monotonic()
                payload = read_raw_payload(self._store, desc)
                conn.send(payload)  # None = gone
                if payload is not None:
                    _record_transfer("push", self._store, desc,
                                     len(payload), time.monotonic() - t0)
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def shutdown(self) -> None:
        self._closed = True
        _drain_acceptor(self._listener, self._acceptor)
        try:
            self._listener.close()
        except Exception:
            pass


def _record_transfer(direction: str, store, desc, nbytes: int,
                     dur_s: float,
                     peer: Optional[str] = None,
                     ctx=None) -> None:
    """Transfer accounting for one cross-node payload move: the
    ``ray_tpu_store_transfer_*`` series, a lifecycle ring event on the
    local store, and (when a trace is in flight or tracing is enabled)
    an ``obj.push``/``obj.pull`` span.  Never fails the transfer path."""
    try:
        telemetry.inc("ray_tpu_store_transfer_bytes_total", nbytes,
                      tags={"direction": direction})
        telemetry.observe("ray_tpu_store_transfer_seconds", dur_s,
                          tags={"op": direction})
        key = desc_key(desc) if isinstance(desc, tuple) else None
        view = getattr(store, "view", None)
        if view is not None and _sv.enabled() and key is not None:
            kind = _sv.E_PUSH if direction == "push" else _sv.E_PULL
            view.push(kind, key, nbytes, peer=peer,
                      detail=f"{dur_s:.6f}")
        parent = ctx if ctx is not None else tracing.current()
        if parent is not None or tracing.is_enabled():
            oid = desc_object_id(desc) if isinstance(desc, tuple) else None
            end_s = time.time()
            # Wall anchor for a monotonic duration, not interval math.
            start_s = end_s - dur_s  # ray-tpu: noqa[RT203]
            tracing.record_span(
                parent, f"obj.{direction}", start_s, end_s,
                attributes={"object_id": oid.hex() if oid else None,
                            "nbytes": nbytes, "peer": peer},
                kind="CLIENT" if direction == "pull" else "SERVER")
    except Exception as e:  # noqa: BLE001
        telemetry.note_swallowed("cluster.record_transfer", e)


def read_raw_payload(store, desc) -> Optional[bytes]:
    """Raw serialized payload bytes of a store-resident descriptor (the
    push side of object transfer, and the materialization path for
    store-less remote clients)."""
    try:
        if desc[0] == "shma":
            return store.read_raw_by_key(desc[4])
        if desc[0] == "shm":
            # Per-object segment (Python store or worker-written):
            # readable by name from any process on this host.
            from .object_store import _open_untracked
            seg = _open_untracked(desc[1], create=False)
            try:
                return bytes(seg.buf[: desc[2]])
            finally:
                seg.close()
    except Exception:
        return None
    return None


class DataClient:
    """Pull side (reference: pull_manager.h:50): pooled connections to peer
    data servers, one in-flight request per peer connection."""

    def __init__(self, token: bytes):
        self._token = token
        self._conns: Dict[Tuple[str, int], Any] = {}
        self._locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._lock = threading.Lock()

    def fetch(self, address: Tuple[str, int], desc) -> Optional[bytes]:
        address = tuple(address)
        with self._lock:
            lk = self._locks.setdefault(address, threading.Lock())
        with lk:
            # Safe bare access: the per-address lock serializes all work
            # on this key, and dict get/setitem are GIL-atomic; _lock
            # only guards the map shape on shutdown.
            conn = self._conns.get(address)  # ray-tpu: noqa[RT401]
            for attempt in (0, 1):
                try:
                    if conn is None:
                        conn = Client(address, authkey=self._token)
                        self._conns[address] = conn
                    conn.send(desc)
                    return conn.recv()
                except Exception:
                    # Covers dead peers (ConnectionRefusedError), token
                    # mismatch (AuthenticationError) and broken pipes alike:
                    # a failed pull must degrade to "object unreachable",
                    # never escape into the dispatch/reply loops.
                    if conn is not None:
                        try:
                            conn.close()
                        except Exception:
                            pass
                        self._conns.pop(address, None)
                    conn = None
            return None

    def shutdown(self) -> None:
        with self._lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except Exception:
                    pass
            self._conns.clear()


class ObjectPuller:
    """Resolve possibly-remote descriptors into local-store descriptors.

    The location oracle maps node_id -> data address; payloads are cached in
    the local store under their ObjectID so repeated consumers stay
    zero-copy (reference: object copies are first-class locations in the
    object directory)."""

    def __init__(self, store, data_client: DataClient,
                 local_node_id_bytes: bytes, resolve_address):
        self._store = store
        self._client = data_client
        self._local = local_node_id_bytes
        self._resolve_address = resolve_address  # node_id_bytes -> (h, p)|None

    def localize(self, desc, ctx=None):
        """Returns a local descriptor, or ("err", payload) if unreachable.
        ``ctx`` parents the pull span on the consuming task's trace (the
        dispatch path runs on node threads with no ambient context)."""
        from . import serialization
        from .exceptions import ObjectLostError

        if not (isinstance(desc, tuple) and desc and desc[0] == "at"):
            return desc
        if desc[1] == self._local:
            return desc[2]
        inner = desc[2]
        oid = desc_object_id(inner)
        if oid is None:
            oid = ObjectID.from_random()  # unparseable: one-off cache key
        # Cache hit?
        local = self._store.descriptor(oid)
        if local is not None:
            return local
        t0 = time.monotonic()
        addr = self._resolve_address(desc[1])
        payload = None
        if addr is not None:
            payload = self._client.fetch(addr, inner)
        if payload is None:
            return ("err", serialization.pack_payload(ObjectLostError(
                f"object {oid} unreachable (owner node gone?)",
                object_id_bytes=oid.binary())))
        local = self._store.put_raw(oid, payload)
        if local is None:
            return ("err", serialization.pack_payload(ObjectLostError(
                f"object {oid} could not be cached locally",
                object_id_bytes=oid.binary())))
        _record_transfer("pull", self._store, inner, len(payload),
                         time.monotonic() - t0,
                         peer=desc[1].hex()[:16], ctx=ctx)
        return local

    def localize_all(self, args: list, kwargs: dict, ctx=None):
        return ([self.localize(d, ctx=ctx) for d in args],
                {k: self.localize(d, ctx=ctx) for k, d in kwargs.items()})


# --------------------------------------------------------------------------
# head side
# --------------------------------------------------------------------------

class RemoteNodeProxy:
    """Head-side stand-in for a joined node: NodeManager's dispatch surface
    over the control connection (reference: raylet client pool)."""

    is_remote = True

    def __init__(self, head: "HeadServer", conn, info: NodeInfo,
                 data_address: Tuple[str, int]):
        self.head = head
        self.conn = conn
        self.info = info
        self.data_address = data_address
        self.store = None  # no local store access on the head
        self._send_lock = threading.Lock()
        self.alive = True
        self.last_seen = time.monotonic()
        # Sequence-numbered redelivery (reference:
        # rpc/retryable_grpc_client.h): every down-message carries
        # (seq, ack-of-up); unacked messages stay in the ring and are
        # resent after a re-attach, so a message written into a dying
        # socket is never silently lost.
        self._down_seq = 0
        self._ring: "deque" = _deque(maxlen=100_000)
        self._ring_overflow = False   # an unacked frame was evicted
        self.last_up_seq = 0          # highest up-seq processed
        self._up_seq_lock = threading.Lock()

    def send(self, msg) -> None:
        with self._send_lock:
            self._down_seq += 1
            frame = ("dseq", self._down_seq, self.last_up_seq, msg)
            if len(self._ring) == self._ring.maxlen:
                # Eviction would silently lose an unacked frame: refuse
                # future re-attach instead (the node rejoins fresh, which
                # is lossy but LOUD — node-death fan-out reruns the work).
                self._ring_overflow = True
            self._ring.append(frame)
            try:
                self.conn.send(frame)
            except (BrokenPipeError, OSError):
                pass  # stays in the ring; resent on re-attach

    def note_up_seq(self, seq: int) -> bool:
        """Atomically claim an up-sequence number; False = duplicate.
        Serialized so an old reader and the re-attached reader can never
        both process the same resent frame."""
        with self._up_seq_lock:
            if seq <= self.last_up_seq:
                return False
            self.last_up_seq = seq
            return True

    def note_up_acked(self, acked_down_seq: int) -> None:
        """The node reports the highest down-seq it received: drop acked
        entries from the resend ring."""
        with self._send_lock:
            while self._ring and self._ring[0][1] <= acked_down_seq:
                self._ring.popleft()

    def reattach(self, conn, last_down_seq: int, ack_msg) -> None:
        """Atomically swap in a fresh control connection, send the raw
        RegisterAck handshake, and replay the unacked tail — all under the
        send lock so concurrent dispatches cannot interleave ahead of the
        redelivered (ordered) frames."""
        with self._send_lock:
            old = self.conn
            self.conn = conn
            while self._ring and self._ring[0][1] <= last_down_seq:
                self._ring.popleft()
            try:
                conn.send(ack_msg)
                for frame in list(self._ring):
                    conn.send(frame)
            except (BrokenPipeError, OSError):
                pass  # node retries the whole rejoin
        self.last_seen = time.monotonic()
        try:
            old.close()
        except Exception:
            pass

    # -- NodeManager surface -------------------------------------------------

    def dispatch_task(self, spec: TaskSpec, resolved_args, resolved_kwargs,
                      target_worker: Optional[WorkerID] = None,
                      pipelined: bool = False) -> None:
        # Untagged descriptors in the head directory are head-local; tag
        # them so the receiving node knows where to pull from.
        hid = self.head.runtime.node_id.binary()
        args = [tag_desc(d, hid) for d in resolved_args]
        kwargs = {k: tag_desc(d, hid) for k, d in resolved_kwargs.items()}
        self.send(DispatchTask(spec, args, kwargs, target_worker,
                               pipelined=pipelined))

    def send_to_worker(self, worker_id: WorkerID, msg) -> None:
        self.send(ToWorker(worker_id, msg))

    def broadcast_stack_dump(self, dump_id: int) -> list:
        """Forward the dump to the remote node; replies flow back as
        UpStackReply.  The head cannot enumerate remote workers, so the
        expected-reply set is empty — the collector waits out its timeout
        instead (see Runtime.ctl_stack_dump)."""
        self.send(StackDumpAll(dump_id))
        return []

    def broadcast_profile(self, req) -> list:
        """Forward a profile capture to the remote node; records flow
        back as UpProfileReply, the expected worker set as
        UpProfileExpect (same contract as broadcast_stack_dump)."""
        self.send(ProfileAll(req))
        return []

    def kill_actor_worker(self, worker_id: WorkerID,
                          force: bool = True) -> None:
        self.send(KillActorWorker(worker_id, force))

    def track_get_pins(self, worker_id, request_id, keys) -> None:
        # Pins for remote readers live on the owning node, not the head.
        pass

    def shutdown(self) -> None:
        self.send(NodeShutdown())


class ClientProxy:
    """Head-side endpoint for a remote driver (reference:
    python/ray/util/client/server — the ray-client proxy that executes
    API calls against the cluster on the client's behalf).

    Clients have no object store: get replies carry raw inline payloads
    (materialized head-side from whichever node owns the object), and puts
    arrive as inline payloads that the head promotes into its store when
    large.  Everything else (submit/wait/kill/ctl) reuses the worker
    protocol directly against the head Runtime."""

    is_remote = False
    is_client = True

    def __init__(self, head: "HeadServer", conn, client_id: WorkerID):
        self.head = head
        self.conn = conn
        self.client_id = client_id
        self.store = head.runtime.node.store
        self._send_lock = threading.Lock()
        self.last_seen = time.monotonic()

    def send(self, msg) -> None:
        try:
            with self._send_lock:
                self.conn.send(msg)
        except (BrokenPipeError, OSError):
            pass

    # on_get_request/on_wait_request reply through this NodeManager-shaped
    # surface; the client is its own single "worker".
    def send_to_worker(self, worker_id: WorkerID, msg) -> None:
        self.send(msg)

    def track_get_pins(self, worker_id, request_id, keys) -> None:
        pass  # client replies are raw copies; nothing stays pinned


class HeadServer:
    """TCP join point on the head: accepts NodeServer registrations, routes
    upstream runtime callbacks, detects node death (EOF + ping timeouts)."""

    def __init__(self, runtime, port: int = 0, token: bytes = DEFAULT_TOKEN,
                 host: str = "0.0.0.0",
                 advertise_host: Optional[str] = None):
        self.runtime = runtime
        self.token = token
        self._listener = Listener((host, port), "AF_INET", authkey=token)
        bound = self._listener.address
        self.advertise_host = advertise_host or "127.0.0.1"
        self.address: Tuple[str, int] = (self.advertise_host, bound[1])
        self.proxies: Dict[NodeID, RemoteNodeProxy] = {}
        self._lock = threading.Lock()
        self._closed = False
        # Pending node-death grace timers: cancelled at shutdown so a
        # mid-grace timer does not outlive the head (sanitizer finding).
        self._death_timers: List[Any] = []
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="head-accept", daemon=True)
        self._acceptor.start()
        sanitizer.spawn(self._ping_loop, name="head-ping")

    # -- membership ----------------------------------------------------------

    def _accept_loop(self) -> None:
        # Safe bare reads: _closed is a monotonic shutdown latch; the
        # worst a stale False costs is one extra loop iteration.
        while not self._closed:  # ray-tpu: noqa[RT401]
            try:
                conn = self._listener.accept()
            except Exception:
                if self._closed:
                    return
                continue
            if self._closed:
                try:
                    conn.close()
                except Exception:
                    pass
                return
            sanitizer.spawn(self._register, args=(conn,),
                            name="head-register")

    def _register(self, conn) -> None:
        try:
            msg: RegisterNode = conn.recv()
        except (EOFError, OSError):
            conn.close()
            return
        if isinstance(msg, RegisterClient):
            self._register_client(conn)
            return
        if not isinstance(msg, RegisterNode):
            conn.close()
            return
        if msg.rejoin_node_id is not None and self._reattach(msg, conn):
            return
        node_id = NodeID.from_random()
        info = NodeInfo(node_id, msg.hostname, ResourceSet(msg.resources),
                        labels={"os_pid": str(msg.os_pid)}, is_head=False)
        proxy = RemoteNodeProxy(self, conn, info, msg.data_address)
        rt = self.runtime
        with self._lock:
            self.proxies[node_id] = proxy
        rt.controller.register_node(info)
        # Identity persists so a WAL-restarted head accepts this node's
        # same-identity re-attach (reference: gcs node table in
        # gcs_init_data.h).
        rt.controller.note_revivable(
            node_id.binary(),
            (msg.hostname, dict(msg.resources),
             int(msg.num_tpu_chips or 0)))
        rt.nodes[node_id] = proxy
        # Raw handshake reply (the seq framing starts after registration).
        try:
            conn.send(RegisterAck(
                node_id.binary(), rt.job_id.binary(), Config.blob(),
                rt.data_server.address, rt.node_id.binary()))
        except (BrokenPipeError, OSError):
            pass
        # Register with the scheduler only after the ack is on the wire so
        # the first dispatch can't race the node's own setup.
        rt.scheduler.add_node(info)
        sanitizer.spawn(self._reader_loop, args=(proxy,),
                        name=f"head-node-{node_id.hex()[:8]}")

    def _reattach(self, msg: RegisterNode, conn) -> bool:
        """A node reconnecting within the grace window re-attaches under
        its existing identity: workers, running tasks and actors survive
        the control-plane blip (reference: raylet reconnect after GCS
        failover; retryable_grpc_client.h)."""
        try:
            nid = NodeID(msg.rejoin_node_id)
        except ValueError:
            return False
        rt = self.runtime
        with self._lock:
            proxy = self.proxies.get(nid)
            if proxy is None or not proxy.alive:
                # Unknown to THIS head process — but a WAL-restarted head
                # accepts re-attaches from nodes whose identity the dead
                # head persisted: their local planes (workers, running
                # tasks) survive the head crash (reference:
                # gcs_init_data.h node table + raylet re-registration).
                wal_revive = proxy is None and \
                    rt.controller.get_revivable(nid.binary()) is not None
            else:
                wal_revive = False
                if proxy._ring_overflow:
                    # The redelivery ring evicted unacked frames: a
                    # silent gap is worse than a loud fresh join.
                    return False
                # Swap under the head lock: the grace timer's death check
                # reads proxy.conn under the same lock, so a re-attach
                # and a death declaration can never interleave (no task
                # runs twice).
                proxy.reattach(conn, msg.last_down_seq, RegisterAck(
                    nid.binary(), rt.job_id.binary(), Config.blob(),
                    rt.data_server.address, rt.node_id.binary(),
                    last_up_seq=proxy.last_up_seq))
        if wal_revive:
            # Blocking work (controller/scheduler registration + the
            # handshake send) runs OUTSIDE the head lock — one sick
            # rejoining peer must not freeze the control plane.
            return self._reattach_from_wal(msg, conn, nid)
        if proxy is None or not proxy.alive:
            return False  # grace expired / truly unknown
        sanitizer.spawn(self._reader_loop, args=(proxy,),
                        name=f"head-node-{nid.hex()[:8]}")
        return True

    def _reattach_from_wal(self, msg: RegisterNode, conn,
                           nid: NodeID) -> bool:
        """Accept a same-identity re-attach at a WAL-restarted head.
        The node keeps its worker pool and running plain tasks; their
        TaskDones ride the node's unacked up-ring and replay against the
        fresh tables.  The ack's ``wal_resumed`` flag tells the node to
        reset its down-seq tracking (this head's sequence space starts
        at zero) and to kill actor workers this head doesn't know
        (revived actors re-create through the normal revival path)."""
        rt = self.runtime
        info = NodeInfo(nid, msg.hostname, ResourceSet(msg.resources),
                        labels={"os_pid": str(msg.os_pid)}, is_head=False)
        proxy = RemoteNodeProxy(self, conn, info, msg.data_address)
        with self._lock:
            if nid in self.proxies:
                return False  # a concurrent re-attach of the same node won
            self.proxies[nid] = proxy
        rt.controller.register_node(info)
        rt.nodes[nid] = proxy
        try:
            conn.send(RegisterAck(
                nid.binary(), rt.job_id.binary(), Config.blob(),
                rt.data_server.address, rt.node_id.binary(),
                last_up_seq=0, wal_resumed=True))
        except (BrokenPipeError, OSError):
            # Undo fully: a half-registered proxy would make the node's
            # RETRY take the normal re-attach path (no wal_resumed), and
            # its stale down-seq tracking would drop every frame from
            # this head forever.
            with self._lock:
                if self.proxies.get(nid) is proxy:
                    self.proxies.pop(nid, None)
            rt.nodes.pop(nid, None)
            rt.controller.mark_node_dead(nid, "wal re-attach ack failed")
            return False
        rt.scheduler.add_node(info)
        sanitizer.spawn(self._reader_loop, args=(proxy,),
                        name=f"head-node-{nid.hex()[:8]}")
        return True

    def _register_client(self, conn) -> None:
        rt = self.runtime
        client_id = WorkerID.from_random()
        proxy = ClientProxy(self, conn, client_id)
        proxy.send(ClientAck(client_id.binary(), rt.job_id.binary(),
                             Config.blob(), rt.node_id.binary()))
        sanitizer.spawn(self._client_reader, args=(proxy,),
                        name=f"head-client-{client_id.hex()[:8]}")

    def _client_reader(self, proxy: ClientProxy) -> None:
        rt = self.runtime
        conn = proxy.conn
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            try:
                self._handle_client(proxy, msg)
            except Exception as e:  # noqa: BLE001
                import traceback
                traceback.print_exc()
                # The client blocks on a reply keyed by request_id — a
                # swallowed error would hang it forever, so always answer.
                self._client_error_reply(proxy, msg, e)
        try:
            conn.close()
        except Exception:
            pass

    @staticmethod
    def _client_error_reply(proxy: ClientProxy, msg, exc: Exception) -> None:
        from . import serialization
        from .protocol import (GetReply, GetRequest, RpcCall, RpcReply,
                               WaitReply, WaitRequest)
        try:
            if isinstance(msg, GetRequest):
                err = ("err", serialization.pack_payload(exc))
                proxy.send(GetReply(msg.request_id,
                                    [err] * len(msg.object_ids)))
            elif isinstance(msg, WaitRequest):
                proxy.send(WaitReply(msg.request_id, []))
            elif isinstance(msg, RpcCall):
                proxy.send(RpcReply(msg.request_id, None, repr(exc)))
        except Exception:  # noqa: BLE001
            pass

    def _handle_client(self, proxy: ClientProxy, msg) -> None:
        from .protocol import (GetRequest, PutFromWorker, RpcCall,
                               SubmitFromWorker, WaitRequest)
        rt = self.runtime
        proxy.last_seen = time.monotonic()
        if isinstance(msg, SubmitFromWorker):
            rt.submit_spec(msg.spec)
        elif isinstance(msg, GetRequest):
            rt.on_get_request(proxy, msg)
        elif isinstance(msg, WaitRequest):
            rt.on_wait_request(proxy, msg)
        elif isinstance(msg, PutFromWorker):
            rt.on_put_from_worker(self._promote_client_put(msg))
        elif isinstance(msg, RpcCall):
            rt.on_rpc_call(proxy, msg)
        elif isinstance(msg, Pong):
            pass

    def _promote_client_put(self, msg) -> Any:
        """Large client puts ride the control pipe as inline payloads;
        promote them into the head store so they live under normal store
        accounting (spill/evict) instead of the directory."""
        desc = msg.desc
        if isinstance(desc, tuple) and desc and desc[0] == "inline" \
                and len(desc[1]) > Config.get("max_inline_object_size"):
            local = self.runtime.node.store.put_raw(msg.object_id, desc[1])
            if local is not None:
                msg.desc = local
        return msg

    def _ping_loop(self) -> None:
        """Liveness probes (reference: gcs_health_check_manager.h:46): a
        node that misses `failure_threshold` ping periods is force-closed,
        which kicks its reader loop into the death path — catching silent
        partitions that never deliver a FIN/RST."""
        period = float(Config.get("health_check_period_s"))
        threshold = int(Config.get("health_check_failure_threshold"))
        while not self._closed:
            time.sleep(period)
            now = time.monotonic()
            with self._lock:
                proxies = list(self.proxies.values())
            for p in proxies:
                if now - p.last_seen > period * threshold:
                    try:
                        p.conn.close()
                    except Exception:
                        pass
                    continue
                p.send(Ping(now))

    def _reader_loop(self, proxy: RemoteNodeProxy) -> None:
        conn = proxy.conn
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError, TypeError, ValueError):
                # TypeError/ValueError: the connection was close()d by a
                # re-attach while this thread sat in recv (cpython's
                # Connection raises TypeError on a None handle).
                break
            try:
                self._handle(proxy, msg)
            except Exception:
                import traceback
                traceback.print_exc()
        with self._lock:
            if proxy.conn is not conn:
                return  # superseded by a re-attach; nothing died
        # Grace window before declaring death: a transient control-plane
        # drop (head hiccup, network blip) re-attaches without failing a
        # single task (reference: gcs reconnect grace in the raylet).
        grace = float(Config.get("node_reconnect_grace_s"))
        if grace > 0:
            # The conn identity check happens inside _on_node_death's
            # locked section, where _reattach also swaps — so a re-attach
            # and a death declaration can never both win.
            t = threading.Timer(
                grace, self._on_node_death, args=(proxy,),
                kwargs={"expect_conn": conn})
            t.daemon = True
            with self._lock:
                if self._closed:
                    # Head shutdown already swept the timers; the EOFs
                    # it caused must not mint new ones behind the sweep.
                    return
                # Prune by finished (fired/cancelled), NOT is_alive():
                # a concurrently appended but not-yet-started Timer is
                # not alive yet, and dropping it here would let it slip
                # past the shutdown cancel sweep.
                self._death_timers = [x for x in self._death_timers
                                      if not x.finished.is_set()]
                self._death_timers.append(t)
            t.start()
        else:
            self._on_node_death(proxy)

    def _on_node_death(self, proxy: RemoteNodeProxy,
                       expect_conn=None) -> None:
        if self._closed:
            return
        with self._lock:
            if expect_conn is not None and proxy.conn is not expect_conn:
                return  # re-attached while the timer was firing
            if not proxy.alive:
                return
            proxy.alive = False
            self.proxies.pop(proxy.info.node_id, None)
        self.runtime.on_node_died(proxy.info.node_id)

    # -- upstream routing ----------------------------------------------------

    def _handle(self, proxy: RemoteNodeProxy, msg) -> None:
        rt = self.runtime
        nid = proxy.info.node_id
        proxy.last_seen = time.monotonic()
        if type(msg) is tuple and msg and msg[0] == "useq":
            _tag, seq, ack_down, msg = msg
            proxy.note_up_acked(ack_down)
            if not proxy.note_up_seq(seq):
                return  # duplicate from a resend overlap
        if isinstance(msg, UpTaskDone):
            rt.on_task_done(msg.msg, nid)
        elif isinstance(msg, UpNoteTaskRunning):
            rt.note_task_running(msg.task_id, nid, msg.worker_id)
        elif isinstance(msg, UpWorkerDied):
            rt.on_worker_died(msg.worker_id, nid, msg.running, msg.actor_id,
                              reason=msg.reason)
        elif isinstance(msg, UpSyncView):
            rt.on_node_view(nid, msg.version, msg.view)
        elif isinstance(msg, BorrowRetained):
            for oid in msg.object_ids:
                rt.mark_escaped(oid)
        elif isinstance(msg, _ContainedRefs):
            rt.note_contained(msg.outer, msg.inner)
        elif isinstance(msg, UpDispatchFailed):
            rt.on_dispatch_failed(msg.spec, msg.reason,
                                  lost_object_bytes=msg.lost_object_bytes)
        elif isinstance(msg, UpPipelineReject):
            rt.on_pipeline_reject(msg.spec, nid)
        elif isinstance(msg, UpFailTask):
            rt.fail_task_bytes(msg.task_id_bytes, msg.return_id_bytes,
                               msg.reason)
        elif isinstance(msg, UpReleaseResources):
            from .ids import PlacementGroupID
            pg = PlacementGroupID(msg.pg_bytes) if msg.pg_bytes else None
            rt.scheduler.release(nid, ResourceSet(msg.resources), pg,
                                 msg.bundle_index)
        elif isinstance(msg, UpBindActor):
            rt.bind_actor_worker(msg.actor_id, nid, msg.worker_id)
        elif isinstance(msg, UpSubmit):
            rt.submit_spec(msg.spec)
        elif isinstance(msg, UpActorState):
            rt.on_actor_state(msg.msg, nid, msg.worker_id)
        elif isinstance(msg, UpStackReply):
            rt.on_stack_reply(msg.msg, nid)
        elif isinstance(msg, UpStackExpect):
            rt.on_stack_expect(msg.dump_id, msg.worker_ids)
        elif isinstance(msg, UpProfileReply):
            rt.on_profile_reply(msg.msg, nid)
        elif isinstance(msg, UpProfileExpect):
            rt.on_profile_expect(msg.profile_id, msg.worker_ids)
        elif isinstance(msg, GetRequest):
            rt.on_get_request(proxy, msg)
        elif isinstance(msg, WaitRequest):
            rt.on_wait_request(proxy, msg)
        elif isinstance(msg, PutFromWorker):
            rt.on_put_from_worker(msg)
        elif isinstance(msg, RpcCall):
            rt.on_rpc_call(proxy, msg)
        elif isinstance(msg, NodeRpc):
            def run_rpc(m=msg):
                try:
                    fn = getattr(rt, "ctl_" + m.method)
                    value = fn(*m.args, **m.kwargs)
                    proxy.send(NodeRpcReply(m.request_id, value))
                except Exception as e:  # noqa: BLE001
                    proxy.send(NodeRpcReply(m.request_id, None, repr(e)))
            if msg.method in rt._BLOCKING_CTL:
                # Long-poll ctl calls must not stall this node's reader.
                sanitizer.spawn(run_rpc, name="node-ctl-rpc")
            else:
                run_rpc()
        elif isinstance(msg, RegisterNode):
            # Second handshake message: the node's real data address (its
            # data server can only bind after the ack delivers the config).
            proxy.data_address = tuple(msg.data_address)
        elif isinstance(msg, Pong):
            pass

    def node_data_address(self, node_id_bytes: bytes):
        rt = self.runtime
        if node_id_bytes == rt.node_id.binary():
            return rt.data_server.address
        with self._lock:
            p = self.proxies.get(NodeID(node_id_bytes))
        return p.data_address if p is not None else None

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            proxies = list(self.proxies.values())
            self.proxies.clear()
        for p in proxies:
            p.shutdown()
        _drain_acceptor(self._listener, self._acceptor)
        try:
            self._listener.close()
        except Exception:
            pass
        # Cancel LAST: the proxy shutdowns above EOF every reader, and a
        # reader that won the race before _closed was observed may have
        # scheduled one more grace timer (cancel-before-start is safe —
        # the timer thread exits immediately).
        with self._lock:
            timers, self._death_timers = self._death_timers, []
        for t in timers:
            t.cancel()


# --------------------------------------------------------------------------
# node server side
# --------------------------------------------------------------------------

class _UpstreamScheduler:
    """scheduler facade NodeManager calls release() on."""

    def __init__(self, server: "NodeServer"):
        self._server = server

    def release(self, node_id, resources: ResourceSet, pg=None,
                bundle_index: int = -1) -> None:
        self._server.send_up(UpReleaseResources(
            resources.to_dict(), pg.binary() if pg is not None else None,
            bundle_index))


class _NodeServerRuntime:
    """The `runtime` facade handed to the node-local NodeManager: every
    callback the driver Runtime would receive is forwarded upstream."""

    def __init__(self, server: "NodeServer", job_id):
        self._server = server
        self.job_id = job_id
        self.scheduler = _UpstreamScheduler(server)

    # NodeManager surface ---------------------------------------------------

    def note_task_running(self, task_id, node_id, worker_id) -> None:
        self._server.send_up(UpNoteTaskRunning(task_id, worker_id))

    def on_task_done(self, msg: TaskDone, node_id) -> None:
        nid = self._server.node_id.binary()
        msg.results = [(oid, tag_desc(d, nid)) for oid, d in msg.results]
        self._server.send_up(UpTaskDone(msg))

    def on_direct_task_done(self, t: tuple) -> bool:
        # Direct actor calls are local-node-only (see submit_actor_direct);
        # everything arriving here takes the full TaskDone path.
        return False

    def on_dispatch_failed(self, spec, reason: str,
                           lost_object_bytes=None) -> None:
        self._server.send_up(UpDispatchFailed(spec, reason,
                                              lost_object_bytes))

    def fail_task_bytes(self, task_id_bytes, return_id_bytes,
                        reason: str) -> None:
        self._server.send_up(UpFailTask(task_id_bytes,
                                        list(return_id_bytes), reason))

    def on_worker_died(self, worker_id, node_id, running, actor_id,
                       reason: str = "") -> None:
        self._server.send_up(UpWorkerDied(worker_id, running, actor_id,
                                          reason))

    def bind_actor_worker(self, actor_id, node_id, worker_id) -> None:
        self._server.send_up(UpBindActor(actor_id, worker_id))

    def submit_spec(self, spec: TaskSpec) -> None:
        self._server.send_up(UpSubmit(spec))

    def on_get_request(self, node, msg: GetRequest) -> None:
        self._server.send_up(msg)

    def on_wait_request(self, node, msg: WaitRequest) -> None:
        self._server.send_up(msg)

    def on_put_from_worker(self, msg: PutFromWorker) -> None:
        msg.desc = tag_desc(msg.desc, self._server.node_id.binary())
        self._server.send_up(msg)

    def on_actor_state(self, msg: ActorStateMsg, node_id, worker_id) -> None:
        self._server.send_up(UpActorState(msg, worker_id))

    def on_rpc_call(self, node, msg: RpcCall) -> None:
        self._server.send_up(msg)

    def on_stack_reply(self, msg, node_id=None) -> None:
        # A worker's stack snapshot: route it up to the head's collector.
        self._server.send_up(UpStackReply(msg))

    def on_profile_reply(self, msg, node_id=None) -> None:
        # A worker's profile capture: route it up to the head's collector.
        self._server.send_up(UpProfileReply(msg))

    def mark_escaped(self, oid) -> None:
        # Borrow escalation from a worker on this node: the owner (head)
        # must pin the object.
        self._server.send_up(BorrowRetained([oid]))

    def note_contained(self, outer, inner) -> None:
        # Containment from a worker on this node: the owner (head)
        # retains the inner refs for the outer object's lifetime.
        from .protocol import ContainedRefs
        self._server.send_up(ContainedRefs(outer, list(inner)))


class NodeServer:
    """A joined cluster node: local NodeManager worker pool + data server,
    driven by DispatchTask messages from the head (reference: the raylet —
    node_manager.cc HandleRequestWorkerLease + object manager, minus local
    scheduling authority, which stays central on the head)."""

    def __init__(self, head_address: Tuple[str, int],
                 token: bytes = DEFAULT_TOKEN,
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 advertise_host: str = "127.0.0.1"):
        self.conn = Client(tuple(head_address), authkey=token)
        self._send_lock = threading.Lock()
        self._head_address = tuple(head_address)
        self._token = token
        # Sequence-numbered redelivery, mirror of RemoteNodeProxy: every
        # up-message carries (seq, ack-of-down); unacked entries resend
        # after a same-identity rejoin.
        self._up_seq = 0
        self._up_ring: "deque" = _deque(maxlen=100_000)
        self._up_ring_overflow = False
        self._last_down = 0

        if num_tpus is None:
            from ..accelerators.tpu import TPUAcceleratorManager
            num_tpus = TPUAcceleratorManager.detect_num_chips()
        node_resources: Dict[str, float] = {
            "CPU": float(num_cpus if num_cpus is not None
                         else (os.cpu_count() or 1)),
        }
        if num_tpus:
            node_resources["TPU"] = float(num_tpus)
        if resources:
            node_resources.update(resources)

        # Register first; the ack carries identity + config.
        self._pre_register(node_resources, num_tpus, token, advertise_host)

    def _pre_register(self, node_resources, num_tpus, token, advertise_host):
        import json

        from .node import NodeManager

        self._reg_args = (node_resources, int(num_tpus or 0))
        # Safe bare access: _pre_register runs single-threaded, before
        # the serve/poll threads that contend on _send_lock exist.
        self.conn.send(RegisterNode(socket.gethostname(),  # ray-tpu: noqa[RT401]
                                    node_resources,
                                    int(num_tpus or 0), ("pending", 0),
                                    os_pid=os.getpid()))
        ack: RegisterAck = self.conn.recv()
        if not isinstance(ack, RegisterAck):
            raise RuntimeError(f"unexpected registration reply: {ack!r}")
        Config.initialize(json.loads(ack.config_blob))
        from .ids import JobID
        self.node_id = NodeID(ack.node_id_bytes)
        self.job_id = JobID(ack.job_id_bytes)
        self.head_data_address = tuple(ack.head_data_address)
        self.head_node_id_bytes = ack.head_node_id_bytes

        info = NodeInfo(self.node_id, socket.gethostname(),
                        ResourceSet(node_resources), is_head=False)
        self._rt = _NodeServerRuntime(self, self.job_id)
        # Per-node session dir: this node's workers log locally, tailed to
        # the node server's stdout (reference: per-node log dirs + log
        # monitor; cross-node shipping rides the job/log tooling).
        from .log_monitor import LogMonitor, create_session_dir
        session = create_session_dir()
        self._rt.session_logs_dir = os.path.join(session, "logs")
        self._log_monitor = LogMonitor(self._rt.session_logs_dir)
        self._log_monitor.start()
        self.node = NodeManager(info, self._rt,
                                num_tpu_chips=int(num_tpus or 0))
        # Cross-node direct channels: this node's workers authenticate
        # with the cluster token and advertise a routable host.
        self.node.direct_token = token
        self.node.direct_host = advertise_host or "127.0.0.1"
        self.data_server = DataServer(self.node.store, token,
                                      advertise_host=advertise_host)
        self.data_address = self.data_server.address
        self.data_client = DataClient(token)
        self._addr_cache: Dict[bytes, Tuple[str, int]] = {}
        self._rpc_lock = threading.Lock()
        # Safe bare writes: registration-time initialization, before any
        # thread that uses the rpc lock exists.
        self._rpc_next = 0  # ray-tpu: noqa[RT401]
        self._rpc_waiters: Dict[int, Any] = {}  # ray-tpu: noqa[RT401]
        self.puller = ObjectPuller(self.node.store, self.data_client,
                                   self.node_id.binary(),
                                   self._resolve_address)
        self._closed = False
        # Set by a NodeShutdown from the head: a deliberate stop, as
        # opposed to a dropped head connection (which triggers rejoin in
        # run_node_server).
        self.stop_requested = False
        # Dispatch and worker-bound messages run on their own ordered
        # queues: localizing args may block on peer pulls (or a NodeRpc to
        # the head, whose reply arrives on the serve thread) — processing
        # them inline would deadlock the control loop.
        import queue as _q
        self._dispatch_q: Any = _q.Queue()
        self._to_worker_q: Any = _q.Queue()
        sanitizer.spawn(self._queue_loop,
                        args=(self._dispatch_q, self._do_dispatch),
                        name="node-dispatch")
        sanitizer.spawn(self._queue_loop,
                        args=(self._to_worker_q, self._do_to_worker),
                        name="node-to-worker")
        # Second message completes the handshake with the real data address.
        self.send_up(RegisterNode(socket.gethostname(), node_resources,
                                  int(num_tpus or 0), self.data_address))
        sanitizer.spawn(self._syncer_loop, name="node-syncer")

    def _syncer_loop(self) -> None:
        """Versioned resource-view reporter (reference: ray_syncer.h:91
        ReporterInterface — a snapshot is broadcast only when it differs
        from the last sent one; the version lets the head drop reordered
        updates)."""
        period = float(Config.get("syncer_period_s"))
        version = 0
        last_view: Optional[Dict[str, Any]] = None
        while not self._closed:
            time.sleep(period)
            try:
                view = self.node.local_view()
            except Exception:  # noqa: BLE001
                continue
            if view == last_view:
                continue
            last_view = view
            version += 1
            self.send_up(UpSyncView(version, view))

    def _resolve_address(self, node_id_bytes: bytes):
        if node_id_bytes == self.head_node_id_bytes:
            return self.head_data_address
        addr = self._addr_cache.get(node_id_bytes)
        if addr is None:
            addr = self.node_rpc("node_data_address", node_id_bytes)
            if addr is not None:
                self._addr_cache[node_id_bytes] = tuple(addr)
        return addr

    # -- control plumbing ----------------------------------------------------

    def send_up(self, msg) -> None:
        with self._send_lock:
            self._up_seq += 1
            frame = ("useq", self._up_seq, self._last_down, msg)
            if len(self._up_ring) == self._up_ring.maxlen:
                self._up_ring_overflow = True  # see _try_rejoin
            self._up_ring.append(frame)
            try:
                self.conn.send(frame)
            except (BrokenPipeError, OSError):
                pass  # stays in the ring; resent after rejoin

    def _try_rejoin(self) -> bool:
        """Reconnect to the head under our existing node identity, keeping
        the local plane (workers, running tasks, actors) alive.  Returns
        False when the head refused (grace expired / head restarted) — the
        caller tears down and rejoins fresh."""
        # Safe bare read: the head connection is down during rejoin, so
        # no send_up() writer is running; a stale False only delays the
        # fresh-rejoin decision one attempt.
        if self._up_ring_overflow:  # ray-tpu: noqa[RT401]
            # Unacked up-frames were evicted: a same-identity rejoin
            # would silently skip them — rejoin fresh instead.
            return False
        grace = max(float(Config.get("node_reconnect_grace_s")), 1.0)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and not self.stop_requested:
            try:
                conn = Client(self._head_address, authkey=self._token)
                node_resources, num_tpus = self._reg_args
                conn.send(RegisterNode(
                    socket.gethostname(), node_resources, num_tpus,
                    self.data_address, os_pid=os.getpid(),
                    rejoin_node_id=self.node_id.binary(),
                    last_down_seq=self._last_down))
                ack = conn.recv()
            except (ConnectionRefusedError, OSError, EOFError):
                time.sleep(0.2)
                continue
            if not isinstance(ack, RegisterAck) or \
                    ack.node_id_bytes != self.node_id.binary():
                # Head forgot us (grace expired or restart with no WAL):
                # a fresh identity means a fresh local plane — reject.
                try:
                    conn.close()
                except Exception:
                    pass
                return False
            wal_resumed = getattr(ack, "wal_resumed", False)
            if wal_resumed:
                # A WAL-restarted head accepted us: its down-seq space
                # restarts at zero (stale _last_down would drop every
                # frame as a duplicate), and actor workers here are
                # unknown to it — revived instances spawn through the
                # normal revival path, so kill the stale ones to prevent
                # two live copies of one actor.
                self._last_down = 0
                self.node.kill_all_actor_workers(
                    reason="head restarted; actor revived elsewhere")
            with self._send_lock:
                self.conn = conn
                # Drop what the head already processed; resend the tail.
                while self._up_ring and \
                        self._up_ring[0][1] <= ack.last_up_seq:
                    self._up_ring.popleft()
                if wal_resumed:
                    # Ring frames bake in ack-of-down values from the
                    # DEAD head's sequence space; replaying them would
                    # make the new head prune its fresh down ring as
                    # "acked".  Rewrite the tail with ack 0.
                    rebuilt = _deque(
                        (("useq", f[1], 0, f[3]) for f in self._up_ring),
                        maxlen=self._up_ring.maxlen)
                    self._up_ring = rebuilt
                for frame in list(self._up_ring):
                    try:
                        conn.send(frame)
                    except (BrokenPipeError, OSError):
                        break
            return True
        return False

    def node_rpc(self, method: str, *args, **kwargs):
        import queue
        with self._rpc_lock:
            self._rpc_next += 1
            rid = self._rpc_next
            q: Any = queue.Queue()
            self._rpc_waiters[rid] = q
        self.send_up(NodeRpc(rid, method, args, kwargs))
        try:
            value, error = q.get(timeout=30.0)
        except Exception:
            value, error = None, "node_rpc timeout"
        finally:
            with self._rpc_lock:
                self._rpc_waiters.pop(rid, None)
        if error:
            return None
        return value

    # -- main loop -----------------------------------------------------------

    def serve_forever(self) -> None:
        while not self._closed:
            conn = self.conn
            try:
                msg = conn.recv()
            except (EOFError, OSError, TypeError, ValueError):
                if self.stop_requested or self._closed:
                    break
                # Transient head drop: re-attach under the same identity
                # so running work survives (retryable client semantics,
                # reference: rpc/retryable_grpc_client.h).
                if self._try_rejoin():
                    continue
                break
            try:
                self._handle(msg)
            except Exception:
                import traceback
                traceback.print_exc()
        self.shutdown()

    def _queue_loop(self, q, fn) -> None:
        while not self._closed:
            item = q.get()
            if item is None:
                return
            try:
                fn(item)
            except Exception:
                import traceback
                traceback.print_exc()

    def _do_dispatch(self, msg: DispatchTask) -> None:
        # Pull spans for arg localization parent on the task's submit
        # span (carried in the spec), so a task tree shows what
        # localizing its inputs cost.
        ctx = None
        tp = getattr(msg.spec, "trace_ctx", None)
        if tp:
            ctx = tracing.SpanContext.from_traceparent(tp)
        args, kwargs = self.puller.localize_all(msg.args, msg.kwargs,
                                                ctx=ctx)
        if getattr(msg, "pipelined", False):
            if not self.node.dispatch_pipelined(msg.spec, args, kwargs):
                self.send_up(UpPipelineReject(msg.spec))
            return
        self.node.dispatch_task(msg.spec, args, kwargs,
                                target_worker=msg.target_worker)

    def _do_to_worker(self, msg: ToWorker) -> None:
        inner = msg.msg
        if isinstance(inner, GetReply):
            inner = self._localize_get_reply(msg.worker_id, inner)
        self.node.send_to_worker(msg.worker_id, inner)

    def _handle(self, msg) -> None:
        if type(msg) is tuple and msg and msg[0] == "dseq":
            _tag, seq, ack_up, msg = msg
            with self._send_lock:
                while self._up_ring and self._up_ring[0][1] <= ack_up:
                    self._up_ring.popleft()
            if seq <= self._last_down:
                return  # duplicate from a resend overlap
            self._last_down = seq
        if isinstance(msg, DispatchTask):
            self._dispatch_q.put(msg)
        elif isinstance(msg, ToWorker):
            self._to_worker_q.put(msg)
        elif isinstance(msg, StackDumpAll):
            ids = self.node.broadcast_stack_dump(msg.dump_id)
            self.send_up(UpStackExpect(msg.dump_id, ids))
        elif isinstance(msg, ProfileAll):
            ids = self.node.broadcast_profile(msg.req)
            self.send_up(UpProfileExpect(msg.req.profile_id, ids))
        elif isinstance(msg, KillActorWorker):
            self.node.kill_actor_worker(msg.worker_id, msg.force)
        elif isinstance(msg, Ping):
            self.send_up(Pong(msg.t))
        elif isinstance(msg, NodeRpcReply):
            with self._rpc_lock:
                q = self._rpc_waiters.get(msg.request_id)
            if q is not None:
                q.put((msg.value, msg.error))
        elif isinstance(msg, FreeObject):
            oid = desc_object_id(msg.desc)
            if oid is not None:
                try:
                    self.node.store.delete(oid)
                except Exception:
                    pass
        elif isinstance(msg, NodeShutdown):
            self.stop_requested = True
            self._closed = True

    def _localize_get_reply(self, worker_id: WorkerID,
                            reply: GetReply) -> GetReply:
        """Pull remote descriptors local and pin them for the reader
        (plasma client-pin semantics on the consuming node)."""
        values = []
        pins: List[bytes] = []
        for d in reply.values:
            local = self.puller.localize(d)
            if isinstance(local, tuple) and local and local[0] == "shma":
                nd = self.node.store.pin_desc_by_key(
                    local[4], pinner=worker_id.hex())
                if nd is not None:
                    pins.append(nd[4])
                    local = nd
            values.append(local)
        if pins:
            self.node.track_get_pins(worker_id, reply.request_id, pins)
        return GetReply(reply.request_id, values, reply.timed_out)

    def shutdown(self) -> None:
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        self._closed = True
        self._dispatch_q.put(None)
        self._to_worker_q.put(None)
        try:
            self.conn.close()
        except Exception:
            pass
        self.data_server.shutdown()
        self.data_client.shutdown()
        self._log_monitor.stop()
        self.node.shutdown()


def run_node_server(head_host: str, head_port: int, token: bytes,
                    num_cpus: Optional[float] = None,
                    num_tpus: Optional[int] = None,
                    resources: Optional[Dict[str, float]] = None,
                    advertise_host: str = "127.0.0.1",
                    reconnect_window_s: float = 60.0) -> None:
    """Run a joined node, re-registering with the head if the control
    connection drops (head restart, reference: raylets reconnecting after
    GCS failover).  The node rejoins with a fresh identity: the restarted
    head re-plans PG bundles and restarts actors onto re-registered nodes
    via the normal node-death/revival paths, so no per-node state needs to
    survive the reconnect."""
    import time as _time
    while True:
        try:
            server = NodeServer(
                (head_host, head_port), token, num_cpus=num_cpus,
                num_tpus=num_tpus, resources=resources,
                advertise_host=advertise_host)
        except (ConnectionRefusedError, OSError, EOFError):
            deadline = _time.monotonic() + reconnect_window_s
            ok = False
            while _time.monotonic() < deadline:
                _time.sleep(1.0)
                try:
                    server = NodeServer(
                        (head_host, head_port), token, num_cpus=num_cpus,
                        num_tpus=num_tpus, resources=resources,
                        advertise_host=advertise_host)
                    ok = True
                    break
                except (ConnectionRefusedError, OSError, EOFError):
                    continue
            if not ok:
                raise
        server.serve_forever()
        if server.stop_requested:
            return
        # serve_forever returned because the head connection dropped; loop
        # to rejoin (the server shut down its local plane — a fresh one
        # spawns clean worker pools).


def main(argv=None) -> int:
    import argparse
    import json
    p = argparse.ArgumentParser(
        description="join a ray_tpu cluster as a worker node")
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--token", default=DEFAULT_TOKEN.decode())
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", default=None,
                   help='JSON dict, e.g. \'{"custom": 2}\'')
    p.add_argument("--advertise-host",
                   default=os.environ.get("RAY_TPU_ADVERTISE_HOST",
                                          "127.0.0.1"),
                   help="peer-reachable IP of this node's data plane")
    args = p.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    run_node_server(host, int(port), args.token.encode(),
                    num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                    resources=json.loads(args.resources)
                    if args.resources else None,
                    advertise_host=args.advertise_host)
    return 0
