"""Accelerator plugin ABC (reference analog:
python/ray/tests/accelerators/ over accelerators/accelerator.py:16)."""

import pytest


class TestAcceleratorRegistry:
    def test_tpu_registered_and_conforms(self):
        from ray_tpu.accelerators.accelerator import (AcceleratorManager,
                                                      all_accelerators,
                                                      get_accelerator)
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager
        assert get_accelerator("TPU") is TPUAcceleratorManager
        assert TPUAcceleratorManager in all_accelerators()
        assert issubclass(TPUAcceleratorManager, AcceleratorManager)
        env = TPUAcceleratorManager.visibility_env([0, 2])
        assert env["TPU_VISIBLE_CHIPS"] == "0,2"
        assert isinstance(TPUAcceleratorManager.detect_num_chips(), int)

    def test_custom_accelerator_plugs_in(self):
        from ray_tpu.accelerators.accelerator import (AcceleratorManager,
                                                      get_accelerator,
                                                      register_accelerator)

        class FakeNPU(AcceleratorManager):
            resource_name = "NPU"

            @staticmethod
            def detect_num_chips() -> int:
                return 2

            @staticmethod
            def visibility_env(chip_ids):
                return {"NPU_VISIBLE": ",".join(map(str, chip_ids))}

        register_accelerator(FakeNPU)
        try:
            assert get_accelerator("NPU") is FakeNPU
            assert FakeNPU.detect_num_chips() == 2
        finally:
            from ray_tpu.accelerators import accelerator as mod
            mod._REGISTRY.pop("NPU", None)

    def test_unnamed_manager_rejected(self):
        from ray_tpu.accelerators.accelerator import (AcceleratorManager,
                                                      register_accelerator)

        class Bad(AcceleratorManager):
            @staticmethod
            def detect_num_chips() -> int:
                return 0

            @staticmethod
            def visibility_env(chip_ids):
                return {}

        with pytest.raises(ValueError):
            register_accelerator(Bad)
