"""User-code lint rules (RT1xx): ``ray_tpu`` usage anti-patterns.

These encode the failure modes the docs warn about (reference: the Ray
anti-pattern catalog — ray.get in a loop, nested ray.get deadlocks,
large objects captured in closures) as static checks over the *shape*
of the call, so they fire in CI instead of in a postmortem.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .lint import (Finding, ModuleContext, Rule, dotted, register,
                   walk_same_scope)

#: Constant elements at/above which a literal counts as "large" for
#: closure-capture purposes (RT103).
LARGE_LITERAL_ELEMS = 64

_UNSERIALIZABLE_CTORS = {
    "threading.Lock": "a threading.Lock",
    "threading.RLock": "a threading.RLock",
    "threading.Condition": "a threading.Condition",
    "threading.Semaphore": "a threading.Semaphore",
    "threading.Event": "a threading.Event",
    "open": "an open file handle",
    "socket.socket": "a socket",
}

_ARRAY_CTORS = {"zeros", "ones", "full", "empty", "arange", "array",
                "rand", "randn"}


def _remote_decorated(node) -> bool:
    """True for ``@remote`` / ``@ray_tpu.remote`` / ``@ray.remote`` and
    their called forms (``@remote(num_tpus=1)``)."""
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted(target)
        if name and name.split(".")[-1] == "remote":
            return True
    return False


def _module_aliases(ctx: ModuleContext) -> Tuple[Set[str], Set[str]]:
    """(module aliases for ray_tpu/ray, bare names bound to their get)."""
    mods: Set[str] = set()
    gets: Set[str] = set()
    for node in ctx.nodes(ast.Import, ast.ImportFrom):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("ray_tpu", "ray"):
                    mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("ray_tpu", "ray"):
                for a in node.names:
                    if a.name == "get":
                        gets.add(a.asname or "get")
    return mods, gets


def _is_framework_get(call: ast.Call, mods: Set[str],
                      gets: Set[str]) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in gets
    if isinstance(func, ast.Attribute) and func.attr == "get":
        return isinstance(func.value, ast.Name) and func.value.id in mods
    return False


def _const_count(node: ast.AST, cap: int) -> int:
    n = 0
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant):
            n += 1
            if n >= cap:
                break
    return n


def _module_level_bindings(tree: ast.Module):
    """Module-level names bound to big literals / array ctors (RT103)
    and to unserializable resources (RT104)."""
    big: Dict[str, str] = {}
    unser: Dict[str, str] = {}
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
        if not targets:
            continue
        v = stmt.value
        if isinstance(v, (ast.List, ast.Tuple)) and \
                _const_count(v, LARGE_LITERAL_ELEMS) >= LARGE_LITERAL_ELEMS:
            for t in targets:
                big[t] = "a large literal"
        elif isinstance(v, ast.Call):
            name = dotted(v.func) or ""
            parts = name.split(".")
            if len(parts) >= 2 and parts[0] in ("np", "numpy", "jnp") and \
                    parts[-1] in _ARRAY_CTORS:
                for t in targets:
                    big[t] = f"an array built by {name}()"
            elif name in _UNSERIALIZABLE_CTORS:
                for t in targets:
                    unser[t] = _UNSERIALIZABLE_CTORS[name]
    return big, unser


def _remote_functions(ctx: ModuleContext):
    """(function, is_method_of_remote_class) for every @remote function
    and every method of a @remote class."""
    for node in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
        if _remote_decorated(node):
            yield node, False
    for node in ctx.nodes(ast.ClassDef):
        if _remote_decorated(node):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield item, True


@register
class NestedBlockingGet(Rule):
    id = "RT101"
    example_bad = (
        "@ray_tpu.remote\n"
        "def outer(ref):\n"
        "    return ray_tpu.get(ref) + 1\n")
    example_good = (
        "@ray_tpu.remote\n"
        "def outer(x):          # take the VALUE\n"
        "    return x + 1\n")
    scope = "user"
    summary = "blocking get() inside a @remote function/actor method"
    rationale = ("A task that blocks on get() occupies its worker while "
                 "waiting for work that needs another worker; under a "
                 "bounded pool, nested gets deadlock.  Restructure so the "
                 "driver composes refs, or pass refs through.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        mods, gets = _module_aliases(ctx)
        if not mods and not gets:
            return
        for fn, is_method in _remote_functions(ctx):
            where = "actor method" if is_method else "remote function"
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        _is_framework_get(node, mods, gets):
                    yield ctx.finding(
                        self, node,
                        f"blocking get() inside {where} {fn.name!r}: "
                        f"nested gets deadlock under a bounded worker "
                        f"pool; pass refs through or restructure")


@register
class GetInLoop(Rule):
    id = "RT102"
    example_bad = (
        "for r in refs:\n"
        "    out.append(ray_tpu.get(r))   # serializes the batch\n")
    example_good = (
        "out = ray_tpu.get(refs)             # one batched get\n")
    scope = "user"
    summary = "get() called per item in a loop over refs"
    rationale = ("get() per loop iteration serializes the whole batch "
                 "(submit-all / get-all or wait() overlaps execution "
                 "with consumption).")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        mods, gets = _module_aliases(ctx)
        if not mods and not gets:
            return
        wait_bound = self._wait_derived(ctx)
        for loop in ctx.nodes(ast.For):
            if not isinstance(loop.target, ast.Name):
                continue
            # Iterating a .remote() call directly consumes a streaming
            # ObjectRefGenerator — per-item get IS the streaming API.
            if isinstance(loop.iter, ast.Call) and \
                    isinstance(loop.iter.func, ast.Attribute) and \
                    loop.iter.func.attr == "remote":
                continue
            # Refs that came back from wait() are already complete:
            # wait-then-get is the recommended pattern, not the bug.
            if isinstance(loop.iter, ast.Name) and \
                    loop.iter.id in wait_bound:
                continue
            lvar = loop.target.id
            for node in walk_same_scope(loop):
                if not (isinstance(node, ast.Call) and
                        _is_framework_get(node, mods, gets)):
                    continue
                if len(node.args) != 1:
                    continue
                arg = node.args[0]
                hits = (isinstance(arg, ast.Name) and arg.id == lvar) or (
                    isinstance(arg, ast.Subscript) and any(
                        isinstance(s, ast.Name) and s.id == lvar
                        for s in ast.walk(arg.slice)))
                if hits:
                    yield ctx.finding(
                        self, node,
                        f"get() on each item of the loop over {lvar!r}: "
                        f"call get() once on the list, or use wait() to "
                        f"overlap completion with consumption")

    @staticmethod
    def _wait_derived(ctx: ModuleContext) -> Set[str]:
        """Names bound (possibly via tuple unpack) from a wait() call."""
        out: Set[str] = set()
        for node in ctx.nodes(ast.Assign):
            if not isinstance(node.value, ast.Call):
                continue
            fname = dotted(node.value.func) or ""
            if fname.split(".")[-1] != "wait":
                continue
            for t in node.targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                out |= {e.id for e in elts if isinstance(e, ast.Name)}
        return out


@register
class LargeCapture(Rule):
    id = "RT103"
    example_bad = (
        "TABLE = np.zeros((1000, 1000))\n"
        "\n"
        "@ray_tpu.remote\n"
        "def f(i):\n"
        "    return TABLE[i].sum()   # re-shipped per submit\n")
    example_good = (
        "ref = ray_tpu.put(TABLE)   # ship once\n"
        "\n"
        "@ray_tpu.remote\n"
        "def f(table, i):\n"
        "    return table[i].sum()\n")
    scope = "user"
    summary = "large literal/array captured in a remote closure"
    rationale = ("Each .remote() call re-serializes captured arguments; "
                 "put() the object once and pass the ref.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        big, _unser = _module_level_bindings(ctx.tree)
        # (a) a large literal passed straight into .remote(...)
        for node in ctx.nodes(ast.Call):
            if not (isinstance(node.func, ast.Attribute) and
                    node.func.attr == "remote"):
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                if isinstance(arg, (ast.List, ast.Tuple)) and \
                        _const_count(arg, LARGE_LITERAL_ELEMS) >= \
                        LARGE_LITERAL_ELEMS:
                    yield ctx.finding(
                        self, arg,
                        "large literal argument to .remote(): put() it "
                        "once and pass the ObjectRef")
        # (b) a module-level array referenced inside a remote function
        # body (captured by the closure serializer on every submit).
        for fn, is_method in _remote_functions(ctx):
            if is_method:
                continue  # actor state lives in one process: fine
            arg_names = {a.arg for a in fn.args.args +
                         fn.args.posonlyargs + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in big and node.id not in arg_names:
                    yield ctx.finding(
                        self, node,
                        f"remote function {fn.name!r} captures "
                        f"module-level {node.id!r} ({big[node.id]}): "
                        f"put() it once and pass the ObjectRef")


@register
class UnserializableCapture(Rule):
    id = "RT104"
    example_bad = (
        "LOCK = threading.Lock()\n"
        "\n"
        "@ray_tpu.remote\n"
        "def f():\n"
        "    with LOCK:              # locks do not pickle\n"
        "        return 1\n")
    example_good = (
        "@ray_tpu.remote\n"
        "def f():\n"
        "    lock = threading.Lock()  # create inside the task\n"
        "    with lock:\n"
        "        return 1\n")
    scope = "user"
    summary = "unserializable object in a .remote() call/closure"
    rationale = ("Locks, file handles and sockets do not survive "
                 "pickling; create them inside the task or hold them in "
                 "actor state.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        _big, unser = _module_level_bindings(ctx.tree)
        for node in ctx.nodes(ast.Call):
            if not (isinstance(node.func, ast.Attribute) and
                    node.func.attr == "remote"):
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                kind = None
                if isinstance(arg, ast.Call):
                    kind = _UNSERIALIZABLE_CTORS.get(dotted(arg.func) or "")
                elif isinstance(arg, ast.Name):
                    kind = unser.get(arg.id)
                if kind:
                    yield ctx.finding(
                        self, arg,
                        f"passing {kind} into .remote(): it cannot be "
                        f"serialized; create it inside the task or keep "
                        f"it in actor state")
        for fn, is_method in _remote_functions(ctx):
            if is_method:
                continue
            arg_names = {a.arg for a in fn.args.args +
                         fn.args.posonlyargs + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in unser and node.id not in arg_names:
                    yield ctx.finding(
                        self, node,
                        f"remote function {fn.name!r} captures "
                        f"module-level {node.id!r} ({unser[node.id]}): "
                        f"it cannot be serialized")


@register
class ActorSelfCall(Rule):
    id = "RT105"
    example_bad = (
        "@ray_tpu.remote\n"
        "class A:\n"
        "    def run(self):\n"
        "        return self.step.remote()   # own busy queue\n")
    example_good = (
        "@ray_tpu.remote\n"
        "class A:\n"
        "    def run(self):\n"
        "        return self.step()          # direct call\n")
    scope = "user"
    summary = "actor method .remote()-calls its own actor"
    rationale = ("self.method.remote() from inside the actor targets the "
                 "actor's own (busy) call queue: with max_concurrency=1 "
                 "a blocking wait on the result never completes.  Call "
                 "the method directly, or go through a separate actor.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.ClassDef):
            if not _remote_decorated(node):
                continue
            methods = {item.name for item in node.body
                       if isinstance(item, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for call in ast.walk(item):
                    if not (isinstance(call, ast.Call) and
                            isinstance(call.func, ast.Attribute) and
                            call.func.attr == "remote"):
                        continue
                    inner = call.func.value  # self.<m>
                    if isinstance(inner, ast.Attribute) and \
                            isinstance(inner.value, ast.Name) and \
                            inner.value.id == "self" and \
                            inner.attr in methods:
                        yield ctx.finding(
                            self, call,
                            f"actor {node.name!r} submits to itself via "
                            f"self.{inner.attr}.remote(): a blocking "
                            f"wait on the result self-deadlocks; call "
                            f"self.{inner.attr}(...) directly")
