"""Dataset: lazy logical plan -> streamed execution over runtime tasks.

Reference analog: python/ray/data/dataset.py:196 Dataset (logical plan
_internal/logical/, StreamingExecutor _internal/execution/
streaming_executor.py:76).  The plan here is a source + a chain of
block-transform stages; consecutive map-like stages fuse into one task
(the reference's operator-fusion rule), and execution streams blocks
through worker tasks with bounded in-flight backpressure.
"""

from __future__ import annotations

import builtins as _builtins
import itertools
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterator, List, Optional, Sequence,
                    Union)

import numpy as np

from .block import Block, BlockAccessor, _normalize


@dataclass
class Stage:
    name: str
    fn: Callable[[Block], Block]          # block -> block
    # map-like stages fuse; all-to-all stages (shuffle/repartition) barrier
    kind: str = "map"


class Dataset:
    """Lazy, immutable; transforms return new Datasets."""

    def __init__(self, source_blocks: List[Any], stages: List[Stage],
                 parallelism: int):
        # source_blocks: list of ObjectRefs or in-memory Blocks
        self._source = source_blocks
        self._stages = stages
        self._parallelism = parallelism

    # ------------------------------------------------------------------ #
    # sources (reference: data/read_api.py)
    # ------------------------------------------------------------------ #

    @staticmethod
    def from_items(items: Sequence[Any], parallelism: int = 8) -> "Dataset":
        items = list(items)
        n = max(1, min(parallelism, len(items) or 1))
        chunks = np.array_split(np.arange(len(items)), n)
        blocks = []
        for c in chunks:
            rows = [_normalize(items[i]) for i in c]
            blocks.append(BlockAccessor.from_rows(rows))
        return Dataset(blocks, [], n)

    @staticmethod
    def range(n: int, parallelism: int = 8) -> "Dataset":
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=np.int64)
        blocks = [{"id": np.arange(a, b)} for a, b in
                  zip(bounds[:-1], bounds[1:]) if b > a]
        return Dataset(blocks, [], parallelism)

    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray],
                   parallelism: int = 8) -> "Dataset":
        n = len(next(iter(arrays.values())))
        bounds = np.linspace(0, n, max(1, parallelism) + 1, dtype=np.int64)
        blocks = [{k: v[a:b] for k, v in arrays.items()}
                  for a, b in zip(bounds[:-1], bounds[1:]) if b > a]
        return Dataset(blocks, [], parallelism)

    @staticmethod
    def from_pandas(df, parallelism: int = 8) -> "Dataset":
        return Dataset.from_numpy(
            {c: df[c].to_numpy() for c in df.columns}, parallelism)

    @staticmethod
    def from_arrow(tables, parallelism: int = 8) -> "Dataset":
        """One or more pyarrow Tables -> Dataset (reference:
        ray.data.from_arrow / from_arrow_refs).  A single table splits
        into ``parallelism`` blocks; a list maps table-per-block —
        numeric columns convert zero-copy."""
        if not isinstance(tables, (list, tuple)):
            block = BlockAccessor.from_arrow(tables)
            if isinstance(block, dict):
                return Dataset.from_numpy(block, parallelism)
            # Arrow layout: split into zero-copy table slices.
            acc = BlockAccessor(block)
            n = acc.num_rows()
            parallelism = max(1, min(parallelism, n or 1))
            bounds = np.linspace(0, n, parallelism + 1, dtype=np.int64)
            return Dataset([acc.slice(int(a), int(b))
                            for a, b in zip(bounds[:-1], bounds[1:])],
                           [], parallelism)
        blocks = [BlockAccessor.from_arrow(t) for t in tables]
        return Dataset(blocks, [], max(1, len(blocks)))

    @staticmethod
    def read_parquet(paths: Union[str, List[str]],
                     parallelism: int = 8) -> "Dataset":
        import glob as g
        if isinstance(paths, str):
            paths = sorted(g.glob(paths)) or [paths]

        from .context import DataContext
        fmt = DataContext.get().block_format

        def load(path):
            import pyarrow.parquet as pq
            return BlockAccessor.from_arrow(pq.read_table(path), fmt)
        return _read_files(paths, load, parallelism)

    @staticmethod
    def read_binary_files(paths: Union[str, List[str]],
                          parallelism: int = 8) -> "Dataset":
        """One row per file: {'bytes', 'path'} (reference:
        _internal/datasource/binary_datasource.py)."""
        from .datasource import expand_paths, load_binary_block
        return _read_files(expand_paths(paths), load_binary_block,
                           parallelism)

    @staticmethod
    def read_images(paths: Union[str, List[str]], *,
                    size: Optional[tuple] = None,
                    mode: Optional[str] = None,
                    parallelism: int = 8) -> "Dataset":
        """Decode image files to {'image', 'path'} rows; ``size=(H, W)``
        resizes at decode, ``mode`` converts color space (reference:
        _internal/datasource/image_datasource.py)."""
        import functools

        from .datasource import (IMAGE_EXTS, expand_paths,
                                 load_image_block)
        loader = functools.partial(load_image_block, size=size, mode=mode)
        return _read_files(expand_paths(paths, IMAGE_EXTS), loader,
                           parallelism)

    @staticmethod
    def read_tfrecord(paths: Union[str, List[str]], *,
                      verify_crc: bool = False,
                      parallelism: int = 8) -> "Dataset":
        """Parse tf.train.Example TFRecord shards into columnar blocks —
        self-contained framing + protobuf codec, no tensorflow
        (reference: _internal/datasource/tfrecords_datasource.py)."""
        import functools

        from .datasource import expand_paths, load_tfrecord_block
        loader = functools.partial(load_tfrecord_block,
                                   verify_crc=verify_crc)
        return _read_files(
            expand_paths(paths, (".tfrecord", ".tfrecords")), loader,
            parallelism)

    @staticmethod
    def read_csv(paths: Union[str, List[str]],
                 parallelism: int = 8) -> "Dataset":
        import glob as g
        if isinstance(paths, str):
            paths = sorted(g.glob(paths)) or [paths]

        from .context import DataContext
        fmt = DataContext.get().block_format

        def load(path):
            import pyarrow.csv as pc
            return BlockAccessor.from_arrow(pc.read_csv(path), fmt)
        return _read_files(paths, load, parallelism)

    @staticmethod
    def read_json(paths: Union[str, List[str]],
                  parallelism: int = 8) -> "Dataset":
        import glob as g
        if isinstance(paths, str):
            paths = sorted(g.glob(paths)) or [paths]

        from .context import DataContext
        fmt = DataContext.get().block_format

        def load(path):
            import pyarrow.json as pj
            return BlockAccessor.from_arrow(pj.read_json(path), fmt)
        return _read_files(paths, load, parallelism)

    # ------------------------------------------------------------------ #
    # transforms
    # ------------------------------------------------------------------ #

    def _with_stage(self, stage: Stage) -> "Dataset":
        return Dataset(self._source, self._stages + [stage],
                       self._parallelism)

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        def apply(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return BlockAccessor.from_rows(rows)
        return self._with_stage(Stage(f"map({fn.__name__})", apply))

    def map_batches(self, fn: Callable[[Block], Block],
                    batch_format: str = "numpy", **_compat) -> "Dataset":
        """``batch_format`` controls what the UDF sees ("numpy" dict by
        default, "pyarrow" for Table-native UDFs on Arrow pipelines);
        the returned value becomes the output block as-is."""
        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            if batch_format == "numpy":
                return fn(acc.to_numpy())
            if batch_format == "pyarrow":
                return fn(acc.to_arrow())
            return fn(block)
        return self._with_stage(Stage(f"map_batches({fn.__name__})",
                                      apply))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        def apply(block: Block) -> Block:
            rows = [o for r in BlockAccessor(block).iter_rows()
                    for o in fn(r)]
            return BlockAccessor.from_rows(rows)
        return self._with_stage(Stage(f"flat_map({fn.__name__})", apply))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        def apply(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = np.array([bool(fn(r)) for r in acc.iter_rows()],
                            dtype=bool)
            return acc.take(np.nonzero(keep)[0])
        return self._with_stage(Stage(f"filter({fn.__name__})", apply))

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]) -> "Dataset":
        def apply(block: Block) -> Block:
            out = dict(BlockAccessor(block).to_numpy())
            out[name] = np.asarray(fn(out))
            return out
        return self._with_stage(Stage(f"add_column({name})", apply))

    def select_columns(self, cols: List[str]) -> "Dataset":
        """reference: Dataset.select_columns."""
        cols = list(cols)

        def apply(block: Block) -> Block:
            b = BlockAccessor(block).to_numpy()
            missing = [c for c in cols if c not in b]
            if b and missing:
                raise KeyError(f"select_columns: missing {missing}")
            return {c: b[c] for c in cols if c in b}
        return self._with_stage(Stage(f"select_columns({cols})", apply))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        """reference: Dataset.drop_columns."""
        drop = set(cols)

        def apply(block: Block) -> Block:
            b = BlockAccessor(block).to_numpy()
            return {k: v for k, v in b.items() if k not in drop}
        return self._with_stage(Stage(f"drop_columns({cols})", apply))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        """reference: Dataset.rename_columns (rejects renames that would
        collide with a surviving column — a silent overwrite loses data)."""
        frozen = dict(mapping)

        def apply(block: Block) -> Block:
            b = BlockAccessor(block).to_numpy()
            names = [frozen.get(k, k) for k in b]
            if len(set(names)) != len(names):
                dup = {n for n in names if names.count(n) > 1}
                raise ValueError(
                    f"rename_columns: duplicate target columns {sorted(dup)}")
            return {frozen.get(k, k): v for k, v in b.items()}
        return self._with_stage(Stage("rename_columns", apply))

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference: Dataset.unique)."""
        from . import executor
        seen: set = set()
        for b in executor.execute_streaming(
                self.select_columns([column])):
            blk = executor.fetch(b)
            if blk and len(blk.get(column, ())):
                seen.update(np.unique(blk[column]).tolist())
        return sorted(seen)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed sort: sample -> range partition -> per-block sort;
        global order is the block order (reference: Dataset.sort over
        planner/exchange/sort_task_spec.py)."""
        return self._with_stage(Stage(
            f"sort[{key}]", lambda b: b,
            kind=f"sort:{key}:{int(descending)}"))

    def groupby(self, key: str) -> "GroupedDataset":
        """reference: Dataset.groupby -> GroupedData (grouped_data.py)."""
        return GroupedDataset(self, key)

    def limit(self, n: int) -> "Dataset":
        """First n rows; consumes the stream only as far as needed
        (reference: Dataset.limit)."""
        from . import executor
        out: List[Any] = []
        count = 0
        for b in executor.execute_streaming(self):
            blk = executor.fetch(b)
            r = BlockAccessor(blk).num_rows()
            if count + r >= n:
                out.append(BlockAccessor(blk).slice(0, n - count))
                count = n
                break
            if r:
                out.append(blk)
                count += r
        return Dataset(out, [], self._parallelism)

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (reference: Dataset.union).  Operand plans
        execute independently; the union is over their output blocks."""
        sources = list(self.materialize()._source)
        for o in others:
            sources.extend(o.materialize()._source)
        return Dataset(sources, [], self._parallelism)

    # -- writes (reference: data write_api / datasource writers) ---------- #

    def _write(self, path: str, writer: Callable[[Block, str], None],
               ext: str) -> List[str]:
        import os

        from . import executor
        os.makedirs(path, exist_ok=True)
        import ray_tpu
        write_remote = ray_tpu.remote(_write_block) \
            if ray_tpu.is_initialized() else None
        outs = []
        for i, b in enumerate(executor.execute_streaming(self)):
            fname = os.path.join(path, f"part-{i:05d}.{ext}")
            if write_remote is not None:
                outs.append(write_remote.remote(writer, b, fname))
            else:
                _write_block(writer, executor.fetch(b), fname)
                outs.append(fname)
        if write_remote is not None:
            outs = ray_tpu.get(outs, timeout=600)
        return outs

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, _parquet_writer, "parquet")

    def write_tfrecord(self, path: str) -> List[str]:
        from .datasource import write_tfrecord_block
        return self._write(path, write_tfrecord_block, "tfrecord")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, _csv_writer, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, _json_writer, "json")

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        return self._with_stage(Stage("random_shuffle", None,  # type: ignore
                                      kind=f"shuffle:{seed}"))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_stage(Stage("repartition", None,  # type: ignore
                                      kind=f"repartition:{num_blocks}"))

    # ------------------------------------------------------------------ #
    # consumption
    # ------------------------------------------------------------------ #

    def materialize(self) -> "Dataset":
        from .executor import execute
        blocks = execute(self)
        return Dataset(blocks, [], self._parallelism)

    def _blocks(self) -> List[Block]:
        from .executor import execute, fetch
        return [fetch(b) for b in execute(self)]

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self._blocks())

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for b in self._blocks():
            for row in BlockAccessor(b).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return self.take(1 << 62)

    def schema(self) -> Dict[str, str]:
        for b in self._blocks():
            if BlockAccessor(b).num_rows():
                return BlockAccessor(b).schema()
        return {}

    def to_pandas(self):
        return BlockAccessor(
            BlockAccessor.concat(self._blocks())).to_pandas()

    def to_arrow_refs(self) -> List[Any]:
        """Execute the plan and return ObjectRefs of pyarrow Tables —
        the zero-copy hand-off to Arrow-native host pipelines (reference:
        Dataset.to_arrow_refs)."""
        import ray_tpu

        from . import executor

        def to_table(block_or_read):
            block = executor._apply_chain([], block_or_read)
            return BlockAccessor(block).to_arrow()

        if not ray_tpu.is_initialized():
            # Driver-local fallback, like every other consumption path.
            return [to_table(b) for b in executor.execute_streaming(self)]
        conv = ray_tpu.remote(to_table)
        out = []
        for b in executor.execute_streaming(self):
            if isinstance(b, ray_tpu.ObjectRef) \
                    or executor._is_read_marker(b):
                out.append(conv.remote(b))
            else:
                out.append(ray_tpu.put(BlockAccessor(b).to_arrow()))
        return out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for b in self._blocks():
            yield from BlockAccessor(b).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     shuffle_seed: Optional[int] = None,
                     batch_format: str = "numpy") -> Iterator[Block]:
        """``batch_format``: "numpy" (dict of ndarrays, the device-feed
        format), "pyarrow" (Tables), or "pandas" (DataFrames) —
        reference: iter_batches batch_format."""
        from .iterator import iter_batches
        it = iter_batches(self, batch_size=batch_size,
                          drop_last=drop_last, shuffle_seed=shuffle_seed)
        if batch_format == "numpy":
            # Arrow pipelines materialize numpy HERE — the consumer
            # boundary — and nowhere earlier.
            return (BlockAccessor(b).to_numpy() for b in it)
        if batch_format == "pyarrow":
            return (BlockAccessor(b).to_arrow() for b in it)
        if batch_format == "pandas":
            return (BlockAccessor(b).to_pandas() for b in it)
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by row count (for per-worker shards;
        reference: Dataset.split / streaming_split)."""
        blocks = self._blocks()
        full = BlockAccessor.concat(blocks)
        total = BlockAccessor(full).num_rows()
        bounds = np.linspace(0, total, n + 1, dtype=np.int64)
        out = []
        for a, b in zip(bounds[:-1], bounds[1:]):
            out.append(Dataset([BlockAccessor(full).slice(int(a), int(b))],
                               [], 1))
        return out

    def num_blocks(self) -> int:
        return len(self._source)

    def stats(self) -> str:
        return (f"Dataset(blocks={len(self._source)}, "
                f"stages={[s.name for s in self._stages]})")

    def __repr__(self):
        return self.stats()


def _read_files(paths: List[str], loader: Callable[[str], Block],
                parallelism: int) -> "Dataset":
    # One read task per file; the loader runs remotely at execution.
    blocks: List[Any] = [("__read__", loader, p) for p in paths]
    return Dataset(blocks, [], parallelism)


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    return Dataset.range(n, parallelism)


def from_items(items, parallelism: int = 8) -> Dataset:
    return Dataset.from_items(items, parallelism)


def from_numpy(arrays, parallelism: int = 8) -> Dataset:
    return Dataset.from_numpy(arrays, parallelism)


def from_pandas(df, parallelism: int = 8) -> Dataset:
    return Dataset.from_pandas(df, parallelism)


def from_arrow(tables, parallelism: int = 8) -> Dataset:
    return Dataset.from_arrow(tables, parallelism)


def read_parquet(paths, parallelism: int = 8) -> Dataset:
    return Dataset.read_parquet(paths, parallelism)


def read_binary_files(paths, parallelism: int = 8, **kw) -> Dataset:
    return Dataset.read_binary_files(paths, parallelism=parallelism, **kw)


def read_images(paths, parallelism: int = 8, **kw) -> Dataset:
    return Dataset.read_images(paths, parallelism=parallelism, **kw)


def read_tfrecord(paths, parallelism: int = 8, **kw) -> Dataset:
    return Dataset.read_tfrecord(paths, parallelism=parallelism, **kw)


def read_csv(paths, parallelism: int = 8) -> Dataset:
    return Dataset.read_csv(paths, parallelism)


def read_json(paths, parallelism: int = 8) -> Dataset:
    return Dataset.read_json(paths, parallelism)


# --------------------------------------------------------------------- #
# grouped datasets (reference: python/ray/data/grouped_data.py)
# --------------------------------------------------------------------- #

_AGG_OPS = ("count", "sum", "mean", "min", "max", "std")


def _agg_block(key: str, aggs: Dict[str, tuple], block: Block) -> Block:
    """Per-reduce-block aggregation: after the hash exchange every key
    lives wholly in one block, so local aggregates are global."""
    if block is None or BlockAccessor(block).num_rows() == 0:
        return {}
    block = BlockAccessor(block).to_numpy()
    uniq, inv = np.unique(block[key], return_inverse=True)
    out: Block = {key: uniq}
    for name, (col, op) in aggs.items():
        if op == "count":
            out[name] = np.bincount(inv, minlength=len(uniq))
            continue
        vals = np.asarray(block[col], np.float64)
        sums = np.bincount(inv, weights=vals, minlength=len(uniq))
        counts = np.bincount(inv, minlength=len(uniq))
        if op == "sum":
            out[name] = sums
        elif op == "mean":
            out[name] = sums / np.maximum(counts, 1)
        elif op == "std":
            sq = np.bincount(inv, weights=vals * vals, minlength=len(uniq))
            mean = sums / np.maximum(counts, 1)
            var = sq / np.maximum(counts, 1) - mean * mean
            out[name] = np.sqrt(np.maximum(var, 0.0))
        elif op == "min":
            acc = np.full(len(uniq), np.inf)
            np.minimum.at(acc, inv, vals)
            out[name] = acc
        elif op == "max":
            acc = np.full(len(uniq), -np.inf)
            np.maximum.at(acc, inv, vals)
            out[name] = acc
        else:
            raise ValueError(f"unknown aggregate op {op!r}")
    return out


def _map_groups_block(key: str, fn: Callable[[Block], Block],
                      block: Block) -> Block:
    if block is None or BlockAccessor(block).num_rows() == 0:
        return {}
    block = BlockAccessor(block).to_numpy()
    uniq, inv = np.unique(block[key], return_inverse=True)
    acc = BlockAccessor(block)
    pieces = []
    for g in _builtins.range(len(uniq)):
        sub = acc.take(np.nonzero(inv == g)[0])
        res = fn(sub)
        if res and BlockAccessor(res).num_rows():
            pieces.append(res)
    return BlockAccessor.concat(pieces) if pieces else {}


class GroupedDataset:
    """reference: GroupedData — aggregate/map_groups over a key."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _exchanged(self) -> Dataset:
        return self._ds._with_stage(Stage(
            f"groupby[{self._key}]", lambda b: b,
            kind=f"groupshuffle:{self._key}"))

    def aggregate(self, **aggs: tuple) -> Dataset:
        """``aggregate(total=("value", "sum"), n=("value", "count"))`` —
        one output row per key, sorted by key within each block."""
        for name, (col, op) in aggs.items():
            if op not in _AGG_OPS:
                raise ValueError(
                    f"{name}: unknown op {op!r}; one of {_AGG_OPS}")
        key = self._key
        frozen = dict(aggs)
        return self._exchanged()._with_stage(Stage(
            "aggregate", lambda b: _agg_block(key, frozen, b)))

    def count(self) -> Dataset:
        return self.aggregate(count=(self._key, "count"))

    def sum(self, col: str) -> Dataset:
        return self.aggregate(**{f"sum({col})": (col, "sum")})

    def mean(self, col: str) -> Dataset:
        return self.aggregate(**{f"mean({col})": (col, "mean")})

    def min(self, col: str) -> Dataset:
        return self.aggregate(**{f"min({col})": (col, "min")})

    def max(self, col: str) -> Dataset:
        return self.aggregate(**{f"max({col})": (col, "max")})

    def std(self, col: str) -> Dataset:
        return self.aggregate(**{f"std({col})": (col, "std")})

    def map_groups(self, fn: Callable[[Block], Block]) -> Dataset:
        """Apply ``fn`` to each key's sub-block (reference:
        GroupedData.map_groups)."""
        key = self._key
        return self._exchanged()._with_stage(Stage(
            "map_groups", lambda b: _map_groups_block(key, fn, b)))


# --------------------------------------------------------------------- #
# block writers (used by Dataset.write_*)
# --------------------------------------------------------------------- #

def _write_block(writer, block_or_ref, path: str) -> str:
    from . import executor
    writer(executor.fetch(block_or_ref), path)
    return path


def _parquet_writer(block: Block, path: str) -> None:
    BlockAccessor(block).to_arrow()
    import pyarrow.parquet as pq
    pq.write_table(BlockAccessor(block).to_arrow(), path)


def _csv_writer(block: Block, path: str) -> None:
    BlockAccessor(block).to_pandas().to_csv(path, index=False)


def _json_writer(block: Block, path: str) -> None:
    BlockAccessor(block).to_pandas().to_json(path, orient="records",
                                             lines=True)
