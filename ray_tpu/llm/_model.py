"""Llama inference forward passes with a paged KV cache.

The training forward (models/llama.py) is full-sequence; inference needs
two extra programs, both jit-compiled with static shapes:

- ``prefill``: run a (padded) prompt through the model, returning the last
  valid position's logits and the per-layer K/V to seed the cache.
- ``decode_step``: one token per active slot, attending over the paged
  cache via block tables through ops/paged_attention.py — the pallas
  block-table kernel on TPU (page-granular DMA, no full-KV gather), the
  exact jnp path elsewhere.

Weights are the training pytree unchanged (init_params layout), so a
trained checkpoint serves directly.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..ops.norms import rms_norm
from ..ops.paged_attention import NEG_INF, paged_decode_attention
from ..ops.rope import rope_frequencies


def _rope_batched(x, cos, sin, positions):
    """x: [B, H, S, D]; positions: [B, S] (per-sequence absolute)."""
    c = cos[positions][:, None]          # [B, 1, S, D/2]
    s = sin[positions][:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def _project_qkv(cfg, layer, h, positions):
    """h: [B, S, E]; positions: [B, S]."""
    dt = cfg.dtype
    q = jnp.einsum("bse,ehd->bhsd", h, layer["wq"].astype(dt))
    k = jnp.einsum("bse,ehd->bhsd", h, layer["wk"].astype(dt))
    v = jnp.einsum("bse,ehd->bhsd", h, layer["wv"].astype(dt))
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    q = _rope_batched(q, cos, sin, positions)
    k = _rope_batched(k, cos, sin, positions)
    return q, k, v


def _mlp(cfg, layer, h):
    dt = cfg.dtype
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"].astype(dt))
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"].astype(dt))
    return jnp.einsum("bsm,me->bse", jax.nn.silu(gate) * up,
                      layer["w_down"].astype(dt))


def prefill(params: Dict[str, Any], tokens: jax.Array, length: jax.Array,
            cfg: LlamaConfig) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """tokens: [1, S_pad]; length: [] valid prompt length.

    Returns (logits at the last valid position [vocab],
             k [L, S_pad, Hkv, D], v [L, S_pad, Hkv, D])."""
    dt = cfg.dtype
    B, S = tokens.shape
    positions = jnp.arange(S)
    x = params["embed"].astype(dt)[tokens]

    def body(x, layer):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, layer, h, positions[None, :])
        # Causal masking suffices: queries at/after `length` are padding
        # whose logits are never read, and valid queries only see valid
        # (earlier) key positions.
        from ..ops.attention import reference_attention
        attn = reference_attention(q, k, v, causal=True)
        attn_out = jnp.einsum("bhsd,hde->bse", attn,
                              layer["wo"].astype(dt))
        x = x + attn_out
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, layer, h2)
        # [S, Hkv, D] per layer for the cache.
        return x, (k[0].transpose(1, 0, 2), v[0].transpose(1, 0, 2))

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.clip(length - 1, 0, S - 1)
    logits = jnp.einsum("e,ev->v", x[0, last].astype(jnp.float32),
                        params["lm_head"].astype(jnp.float32))
    return logits, ks, vs




def write_prefill(kv_pages, ks, vs, page_ids, offs):
    """Scatter a prefilled prompt's K/V into every layer's pages in ONE
    device program (kv_pages: per-layer tuple of combined
    [NP, page, 2*Hkv, D] arrays, donated) — per-layer host-dispatched
    scatters would cost 2*layers dispatches per admission, which over a
    high-latency host link takes longer than the decode itself.

    ks/vs: [L, S_pad, Hkv, D] from prefill; page_ids/offs: [S_pad]
    (positions past the real prompt length point at reserved page 0, so
    the scatter shape is bucket-static)."""
    from ..ops.paged_attention import combine_kv
    kv = list(kv_pages)
    dt = kv[0].dtype
    for li in range(len(kv)):
        comb = combine_kv(ks[li], vs[li]).astype(dt)   # [S_pad, 2Hkv, D]
        kv[li] = kv[li].at[page_ids, offs, :, :].set(comb)
    return tuple(kv)


def prefill_chunk(params: Dict[str, Any], kv_pages,
                  tokens: jax.Array, start: jax.Array, length: jax.Array,
                  block_table: jax.Array, cfg: LlamaConfig,
                  page_size: int):
    """Incremental (chunked) prefill: run ``length`` prompt tokens that
    begin at absolute position ``start`` through the model, writing
    their K/V into this sequence's pages and attending over ALL cache
    positions ``[0, start+length)`` — earlier chunks' K/V are read back
    from the paged cache, so a long prompt prefills as a series of small
    bounded programs interleaved with decode steps instead of one
    monolithic program that stalls every active decode (reference
    analog: vLLM chunked prefill / Sarathi-style piggybacking).

    tokens: [1, C] chunk-bucket-padded; start/length: scalars;
    block_table: [P] page ids for this sequence.  Returns (logits at the
    chunk's last valid position [vocab], new kv_pages).
    """
    import math as _math

    from ..ops.paged_attention import combine_kv

    dt = cfg.dtype
    _B, C = tokens.shape
    P = block_table.shape[0]
    S = P * page_size
    Hkv, D = cfg.kv_heads, cfg.head_dim
    group = cfg.heads // Hkv
    idx = jnp.arange(C)
    positions = start + idx                       # [C] absolute
    total = start + length
    valid = idx < length
    # Rope table lookups clamp; writes for padding rows land on reserved
    # page 0 (never referenced by any block table).
    rope_pos = jnp.minimum(positions, cfg.max_seq_len - 1)
    page_ids = jnp.where(
        valid, block_table[jnp.clip(positions // page_size, 0, P - 1)], 0)
    offs = jnp.where(valid, positions % page_size, 0)
    kv_pos = jnp.arange(S)
    x = params["embed"].astype(dt)[tokens]        # [1, C, E]

    n_layers = params["blocks"]["wq"].shape[0]
    kv_pages = list(kv_pages)
    # Jitted by callers (engine's prefill-chunk jit / disagg prefill): the
    # layer loop unrolls at trace time, it never dispatches op-by-op.
    for li in range(n_layers):  # ray-tpu: noqa[RT506]
        layer = jax.tree.map(lambda a, li=li: a[li], params["blocks"])
        kv = kv_pages[li]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, layer, h, rope_pos[None, :])
        # Write this chunk's K/V first, then gather the WHOLE sequence
        # back from pages: chunk-internal causality rides the same mask
        # as cross-chunk context.
        comb = combine_kv(k[0].transpose(1, 0, 2),
                          v[0].transpose(1, 0, 2)).astype(kv.dtype)
        kv = kv.at[page_ids, offs, :, :].set(comb)
        kv_pages[li] = kv
        pages = jnp.take(kv, block_table, axis=0)  # [P, page, 2Hkv, D]
        ks = pages[:, :, 0::2, :].reshape(S, Hkv, D)
        vs = pages[:, :, 1::2, :].reshape(S, Hkv, D)
        kh = ks.transpose(1, 0, 2)                 # [Hkv, S, D]
        vh = vs.transpose(1, 0, 2)
        if group > 1:
            kh = jnp.repeat(kh, group, axis=0)
            vh = jnp.repeat(vh, group, axis=0)
        scores = jnp.einsum("hcd,hsd->hcs", q[0], kh,
                            preferred_element_type=jnp.float32) \
            / _math.sqrt(D)
        mask = (kv_pos[None, :] <= positions[:, None]) & \
               (kv_pos[None, :] < total)           # [C, S]
        scores = jnp.where(mask[None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("hcs,hsd->hcd", probs.astype(vh.dtype), vh)
        attn_out = jnp.einsum("hcd,hde->ce", attn, layer["wo"].astype(dt))
        x = x + attn_out[None]
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, layer, h2)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.clip(length - 1, 0, C - 1)
    logits = jnp.einsum("e,ev->v", x[0, last].astype(jnp.float32),
                        params["lm_head"].astype(jnp.float32))
    return logits, tuple(kv_pages)


def decode_step(params: Dict[str, Any], kv_pages,
                tokens: jax.Array, positions: jax.Array,
                block_tables: jax.Array, active: jax.Array,
                cfg: LlamaConfig, page_size: int):
    """One decode step for every slot.

    tokens: [B] last sampled token per slot; positions: [B] their position;
    block_tables: [B, P] page ids; active: [B] bool.
    Returns (logits [B, vocab], new kv_pages) — cache arrays are updated
    in place via donation.

    Cache layout: a TUPLE of per-layer COMBINED page arrays
    ``[num_pages, page_size, 2*Hkv, D]`` (K even / V odd combined-head
    indices — the ragged-paged-attention kernel's native layout).  Each
    leaf takes exactly ONE scatter per step whose [2*Hkv, D] window is
    fully contiguous at a leading (page, offset) index — the layout this
    replaced (split K/V, heads leading) needed 48 strided scatters per
    step that cost ~3x the model's matmuls on v5e."""
    from ..ops.paged_attention import combine_kv
    dt = cfg.dtype
    B = tokens.shape[0]
    x = params["embed"].astype(dt)[tokens][:, None, :]     # [B, 1, E]
    seq_lens = jnp.where(active, positions + 1, 0)
    page_idx = jnp.take_along_axis(
        block_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    # Inactive slots park their write on reserved page 0 (never read)
    # instead of a predicated read-modify-write of live pages.
    page_idx = jnp.where(active, page_idx, 0)
    page_off = jnp.where(active, positions % page_size, 0)

    n_layers = params["blocks"]["wq"].shape[0]
    kv_pages = list(kv_pages)
    for li in range(n_layers):
        layer = jax.tree.map(lambda a, li=li: a[li], params["blocks"])
        kv = kv_pages[li]
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _project_qkv(cfg, layer, h, positions[:, None])
        # ONE combined scatter: target kv[page_idx, page_off] is
        # [B, 2*Hkv, D] with a contiguous window per index.
        comb = combine_kv(k[:, :, 0, :], v[:, :, 0, :]).astype(kv.dtype)
        kv = kv.at[page_idx, page_off, :, :].set(comb,
                                                 unique_indices=False)
        kv_pages[li] = kv
        attn = paged_decode_attention(q[:, :, 0, :], kv, block_tables,
                                      seq_lens, page_size)
        attn_out = jnp.einsum("bhd,hde->be", attn, layer["wo"].astype(dt))
        x = x + attn_out[:, None, :]
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _mlp(cfg, layer, h2)
    kv_pages = tuple(kv_pages)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # bf16 reads with f32 MXU accumulation: casting lm_head to f32 would
    # materialize a 4-byte copy of the largest matrix every step.
    logits = jnp.einsum("be,ev->bv", x[:, 0, :], params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits.astype(jnp.float32), kv_pages


def decode_chunk(params: Dict[str, Any], kv_pages,
                 tokens: jax.Array, positions: jax.Array,
                 block_tables: jax.Array, active: jax.Array,
                 rng_key: jax.Array, cfg: LlamaConfig, page_size: int,
                 steps: int, temperature: float, top_k: int):
    """Device-resident multi-token decode: ``steps`` decode iterations
    under one jit with ON-DEVICE sampling, so the host syncs once per
    chunk instead of once per token.  On a TPU behind a high-latency
    host link (or any setup where per-step d2h dominates), this is the
    difference between latency-bound and compute-bound decode — the
    TPU-native analog of the reference engine's multi-step scheduling
    (reference: vLLM num_scheduler_steps / multi-step decode).

    tokens/positions/active: [B] as in decode_step.  Returns
    (sampled [steps, B], new positions, kv_pages).  Sampling:
    greedy when temperature <= 0 else top-k/categorical, per-step keys
    folded from ``rng_key``.  Stop tokens are enforced by the HOST after
    the chunk (bounded overgeneration by design)."""

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, NEG_INF, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(
            jnp.int32)

    def body(carry, i):
        toks, pos, kv = carry
        logits, kv = decode_step(params, kv, toks, pos,
                                 block_tables, active, cfg, page_size)
        nxt = sample(logits, jax.random.fold_in(rng_key, i))
        nxt = jnp.where(active, nxt, toks)
        pos = jnp.where(active, pos + 1, pos)
        return (nxt, pos, kv), nxt

    # lax.scan keeps one copy of the (donated) cache live across steps.
    import jax.lax as lax
    (_, positions, kv_pages), out = lax.scan(
        body, (tokens, positions, kv_pages),
        jnp.arange(steps, dtype=jnp.int32))
    return out, positions, kv_pages
