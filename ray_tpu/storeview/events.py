"""Bounded object-lifecycle event ring (the data-plane flight recorder).

Reference analog: ``ray memory`` reconstructs object state from the
reference counter + plasma metadata at query time
(src/ray/object_manager/pull_manager.h:50, push_manager.h:28); the
lifecycle *history* — when did this object spill, who pulled it, what
did localizing it cost — is never kept.  Here every store mutation lands
in one bounded ring per store instance, folded lazily into a per-object
latest-state index, so those questions are point lookups.

Hot-path contract (same as ``schedview.DecisionRing``): recording is ONE
``deque.append`` of a tuple plus an integer bump — no locks, no hex
encoding, no dict churn.  Folding tuples into per-object state and
everything stringy happen at read time.  The put/get hot path is gated
by the dataplane bench's <2% overhead budget, so additions here must
stay on that contract.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, Iterable, List, Optional

# -- event kinds (closed vocabulary) ----------------------------------------
#
# `ray-tpu obj why`, state.explain_object(), the memory summary's leak
# scan and the dataplane bench's lifecycle assertions all match on these,
# so additions here must ride a README update.
E_CREATE = "create"    # buffer allocated (unsealed)
E_SEAL = "seal"        # object immutable, readable
E_GET = "get"          # local read (descriptor/buffer handed out)
E_PIN = "pin"          # reader pin taken (detail = pinner token)
E_UNPIN = "unpin"      # reader pin released
E_PUSH = "push"        # served to a remote node (data-server side)
E_PULL = "pull"        # localized from a remote node (puller side)
E_SPILL = "spill"      # written to disk under memory pressure
E_RESTORE = "restore"  # read back from spill file
E_EVICT = "evict"      # dropped from memory (native arena LRU)
E_DELETE = "delete"    # removed from the store

EVENT_KINDS = (E_CREATE, E_SEAL, E_GET, E_PIN, E_UNPIN, E_PUSH, E_PULL,
               E_SPILL, E_RESTORE, E_EVICT, E_DELETE)

#: pinner tokens kept per object in the folded index (display bound).
MAX_PINNERS = 8
#: sealed-never-read age after which an object counts as a leak
#: candidate in the memory summary.
LEAK_TTL_S = float(os.environ.get("RAY_TPU_STORE_LEAK_TTL_S", "60"))
#: ring events returned per object by ``explain``.
EXPLAIN_EVENT_TAIL = 50

# -- global enable switch ---------------------------------------------------

_enabled = os.environ.get("RAY_TPU_STORE_TRACE", "1").strip().lower() \
    not in ("0", "false", "no", "off")


def enabled() -> bool:
    """Whether store lifecycle tracing is on (module-global: one read on
    the put/get path)."""
    return _enabled


def set_enabled(value: bool) -> None:
    """Toggle lifecycle tracing (the dataplane bench's off/on overhead
    reps; operators use RAY_TPU_STORE_TRACE=0)."""
    global _enabled
    _enabled = bool(value)


class StoreEventRing:
    """Bounded, lazily-folded ring of object lifecycle records.

    ``push`` is on the per-op hot path; it appends a raw tuple
    ``(mono, kind, key, nbytes, peer, detail)`` (``key`` stays raw
    bytes — hex encoding is fold-time) and bumps a plain int counter.
    The per-object latest-state index (what ``explain`` and the memory
    summary read) is built at fold time under the ring lock.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = max(64, int(capacity))
        # maxlen bounds the unfolded backlog in O(1) on the hot path; a
        # threshold-triggered fold here would charge the whole fold
        # (µs per event) against whichever put/get crossed the line.
        self._pending: deque = deque(maxlen=self.capacity)
        self._records: deque = deque()
        self._latest: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.num_dropped = 0
        # Plain-int per-kind totals (flushed into the telemetry counters
        # by the head's rate-limited publisher, never on hot path).
        self.counts: Dict[str, int] = {}
        # Cumulative transfer bytes by direction ("push"/"pull"): node
        # processes record transfers into their own telemetry registry,
        # which never reaches the head's merged scrape — the head folds
        # these tallies (synced via the node view) into
        # ray_tpu_store_transfer_bytes_total instead.
        self.transfer_bytes: Dict[str, int] = {}

    # -- hot path -----------------------------------------------------------

    def push(self, kind: str, key: bytes, nbytes: int = 0,
             peer: Optional[str] = None,
             detail: Optional[str] = None,
             _mono=time.monotonic) -> None:
        # One clock read per event; records carry the monotonic stamp
        # only, and snapshot() maps mono->wall through a single offset
        # computed at read time.  Folding happens ONLY at read time: if
        # no reader drains the ring, the bounded deque discards the
        # oldest unfolded event instead of paying a fold here.
        # Documented lock-free hot path (see class docstring): deque ops
        # are thread-safe, num_dropped/counts are advisory single-writer
        # counters, and _fold() drains under the lock at read time.
        p = self._pending  # ray-tpu: noqa[RT401]
        if len(p) == self.capacity:
            self.num_dropped += 1  # ray-tpu: noqa[RT401]
        p.append((_mono(), kind, key, nbytes, peer, detail))
        c = self.counts  # ray-tpu: noqa[RT401]
        try:
            c[kind] += 1
        except KeyError:
            c[kind] = 1
        if kind == E_PUSH or kind == E_PULL:
            t = self.transfer_bytes  # ray-tpu: noqa[RT401]
            try:
                t[kind] += nbytes
            except KeyError:
                t[kind] = nbytes

    # -- folding ------------------------------------------------------------

    def _fold(self) -> None:
        with self._lock:
            while True:
                try:
                    rec = self._pending.popleft()
                except IndexError:
                    break
                self._records.append(rec)
                if len(self._records) > self.capacity:
                    self._records.popleft()
                    self.num_dropped += 1
                self._apply(rec)

    def _apply(self, rec: tuple) -> None:
        """Fold one record into the per-object state (under _lock)."""
        mono, kind, key, nbytes, peer, detail = rec
        hexkey = key.hex() if isinstance(key, (bytes, bytearray)) \
            else str(key)
        st = self._latest.get(hexkey)
        if st is None:
            st = {
                "object_id": hexkey, "state": "created", "nbytes": 0,
                "created_mono": mono, "sealed_mono": None,
                "reads": 0, "last_read_mono": None,
                "pins": 0, "pinners": [],
                "spills": 0, "restores": 0, "spilled": False,
                "pulls": 0, "pull_bytes": 0, "pull_seconds": 0.0,
                "pushes": 0, "push_bytes": 0,
                "last_peer": None, "last_mono": mono,
            }
            self._latest[hexkey] = st
        st["last_mono"] = mono
        self._latest.move_to_end(hexkey)
        if nbytes:
            st["nbytes"] = nbytes
        if peer is not None:
            st["last_peer"] = peer
        if kind == E_CREATE:
            st["created_mono"] = mono
            if st["state"] in ("deleted", "evicted"):
                st["state"] = "created"
                st["spilled"] = False
        elif kind == E_SEAL:
            st["sealed_mono"] = mono
            if not st["spilled"]:
                st["state"] = "sealed"
        elif kind == E_GET:
            st["reads"] += 1
            st["last_read_mono"] = mono
        elif kind == E_PIN:
            st["pins"] += 1
            token = detail or "?"
            if token not in st["pinners"] and \
                    len(st["pinners"]) < MAX_PINNERS:
                st["pinners"].append(token)
        elif kind == E_UNPIN:
            st["pins"] = max(0, st["pins"] - 1)
            token = detail or "?"
            if st["pins"] == 0:
                st["pinners"] = []
            elif token in st["pinners"]:
                st["pinners"].remove(token)
        elif kind == E_SPILL:
            st["spills"] += 1
            st["spilled"] = True
            st["state"] = "spilled"
        elif kind == E_RESTORE:
            st["restores"] += 1
            st["spilled"] = False
            st["state"] = "sealed"
        elif kind == E_EVICT:
            st["state"] = "evicted"
        elif kind == E_PULL:
            st["pulls"] += 1
            st["pull_bytes"] += nbytes
            try:
                st["pull_seconds"] += float(detail or 0.0)
            except (TypeError, ValueError):
                pass
        elif kind == E_PUSH:
            st["pushes"] += 1
            st["push_bytes"] += nbytes
        elif kind == E_DELETE:
            st["state"] = "deleted"
            st["pins"] = 0
            st["pinners"] = []
        if len(self._latest) > self.capacity:
            self._latest.popitem(last=False)

    # -- reads --------------------------------------------------------------

    @staticmethod
    def _state_dict(st: Dict[str, Any], now_mono: float,
                    wall_offset: float) -> Dict[str, Any]:
        """Display form of one folded per-object state: ages instead of
        raw monotonic stamps."""
        out = {k: v for k, v in st.items()
               if not k.endswith("_mono")}
        out["pinners"] = list(st["pinners"])
        out["age_s"] = round(now_mono - st["created_mono"], 3)
        out["time"] = st["last_mono"] + wall_offset
        if st["sealed_mono"] is not None:
            out["sealed_age_s"] = round(now_mono - st["sealed_mono"], 3)
        if st["last_read_mono"] is not None:
            out["idle_s"] = round(now_mono - st["last_read_mono"], 3)
        if st["pulls"]:
            out["pull_avg_ms"] = round(
                1e3 * st["pull_seconds"] / st["pulls"], 3)
        return out

    @staticmethod
    def _to_dict(rec: tuple, wall_offset: float) -> Dict[str, Any]:
        mono, kind, key, nbytes, peer, detail = rec
        return {
            "time": mono + wall_offset, "mono": mono, "kind": kind,
            "object_id": key.hex() if isinstance(key, (bytes, bytearray))
            else str(key),
            "nbytes": nbytes, "peer": peer, "detail": detail,
        }

    def snapshot(self, object_id: Optional[str] = None,
                 limit: int = 200) -> List[Dict[str, Any]]:
        """Newest-last lifecycle records; ``object_id`` filters (hex
        prefix ok: operators paste truncated ids)."""
        self._fold()
        out: List[Dict[str, Any]] = []
        # Mono->wall basis shift for display, not an interval.
        wall_offset = time.time() - time.monotonic()  # ray-tpu: noqa[RT203]
        with self._lock:
            records = list(self._records)
        for rec in reversed(records):
            if object_id is not None:
                key = rec[2]
                hexkey = key.hex() if isinstance(key, (bytes, bytearray)) \
                    else str(key)
                if not hexkey.startswith(object_id):
                    continue
            out.append(self._to_dict(rec, wall_offset))
            if len(out) >= limit:
                break
        out.reverse()
        return out

    def latest_index(self, limit: int = 0) -> List[Dict[str, Any]]:
        """Folded per-object states, most recently touched first
        (``limit`` 0 = all tracked)."""
        self._fold()
        now_mono = time.monotonic()
        wall_offset = time.time() - now_mono  # ray-tpu: noqa[RT203]
        with self._lock:
            states = [dict(st) for st in reversed(self._latest.values())]
        if limit:
            states = states[:limit]
        return [self._state_dict(st, now_mono, wall_offset)
                for st in states]

    def explain(self, object_id: str) -> Dict[str, Any]:
        """Point lookup behind ``ray-tpu obj why`` (hex prefix ok):
        folded state + the object's recent lifecycle events."""
        self._fold()
        prefix = (object_id or "").lower()
        with self._lock:
            matches = [k for k in self._latest if k.startswith(prefix)]
        if not matches:
            return {"status": "unknown",
                    "detail": "no lifecycle events recorded for this id "
                              "(ring bounded, or tracing disabled)"}
        if len(matches) > 1:
            return {"status": "ambiguous",
                    "matches": sorted(matches)[:8]}
        hexkey = matches[0]
        now_mono = time.monotonic()
        wall_offset = time.time() - now_mono  # ray-tpu: noqa[RT203]
        with self._lock:
            st = dict(self._latest[hexkey])
        out = self._state_dict(st, now_mono, wall_offset)
        out["status"] = "ok"
        out["events"] = self.snapshot(object_id=hexkey,
                                      limit=EXPLAIN_EVENT_TAIL)
        return out

    def pinners_of(self, key: bytes) -> List[str]:
        """Pinner tokens recorded for one object (exact raw key)."""
        self._fold()
        with self._lock:
            st = self._latest.get(key.hex())
            return list(st["pinners"]) if st is not None else []

    def top_pinned(self, n: int = 3) -> List[Dict[str, Any]]:
        """Largest currently-pinned objects with their pinners — the
        actionable half of an ObjectStoreFullError message."""
        self._fold()
        with self._lock:
            pinned = [dict(st) for st in self._latest.values()
                      if st["pins"] > 0 and st["state"] not in
                      ("deleted", "evicted")]
        pinned.sort(key=lambda st: st["nbytes"], reverse=True)
        return [{"object_id": st["object_id"], "nbytes": st["nbytes"],
                 "pins": st["pins"], "pinners": list(st["pinners"])}
                for st in pinned[:n]]

    @staticmethod
    def _is_incarnation_token(tok: str) -> bool:
        """Pinner labels that name a worker/process incarnation (an id
        hex) can be liveness-checked; descriptive labels ("driver",
        "ckpt_stage", "?") cannot and never count as dead."""
        if len(tok) < 16:
            return False
        try:
            int(tok, 16)
        except ValueError:
            return False
        return True

    def leak_candidates(self, ttl_s: Optional[float] = None,
                        live_tokens: Optional[Iterable[str]] = None
                        ) -> List[Dict[str, Any]]:
        """Objects that look leaked: sealed but never read past the TTL,
        or pinned only by incarnation tokens no longer alive (pass the
        current worker-id set as ``live_tokens``)."""
        ttl_s = LEAK_TTL_S if ttl_s is None else ttl_s
        self._fold()
        now_mono = time.monotonic()
        wall_offset = time.time() - now_mono  # ray-tpu: noqa[RT203]
        live = set(live_tokens) if live_tokens is not None else None
        out: List[Dict[str, Any]] = []
        with self._lock:
            states = [dict(st) for st in self._latest.values()]
        for st in states:
            if st["state"] in ("deleted", "evicted"):
                continue
            anchor = st["sealed_mono"] if st["sealed_mono"] is not None \
                else st["created_mono"]
            if st["reads"] == 0 and st["pins"] == 0 and \
                    st["sealed_mono"] is not None and \
                    now_mono - anchor > ttl_s:
                rec = self._state_dict(st, now_mono, wall_offset)
                rec["reason"] = "sealed_never_read"
                out.append(rec)
                continue
            if st["pins"] > 0 and live is not None and st["pinners"] and \
                    all(self._is_incarnation_token(tok) and tok not in live
                        for tok in st["pinners"]):
                rec = self._state_dict(st, now_mono, wall_offset)
                rec["reason"] = "pinned_by_dead_incarnation"
                out.append(rec)
        out.sort(key=lambda r: r["nbytes"], reverse=True)
        return out

    def stats(self) -> Dict[str, Any]:
        self._fold()
        with self._lock:
            size = len(self._records)
            tracked = len(self._latest)
        return {"counts": dict(self.counts),
                "total": sum(self.counts.values()),
                "size": size, "tracked": tracked,
                "capacity": self.capacity,
                "num_dropped": self.num_dropped}

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._records.clear()
            self._latest.clear()
            self.counts = {}
            self.num_dropped = 0
