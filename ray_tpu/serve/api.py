"""Serve core: deployments, replicas, router, handles, HTTP ingress.

The control plane lives in the ``SERVE_CONTROLLER`` actor (reference:
_private/controller.py:126 — ServeController as a detached actor): it
owns replica actors, so deployments keep serving after the creating
driver exits.  Versioned replica-set snapshots flow through the cluster
KV (reference: _private/long_poll.py LongPollHost); each consuming
process runs a local ``_Router`` that rebuilds replica handles from the
snapshot and does power-of-two-choices over its own in-flight counts
(reference: pow_2_router.py — per-router counts, exactly the reference's
model), pushing totals back to the controller for request-based
autoscaling.  The optional HTTP proxy is an aiohttp app on a daemon
thread (reference: proxy.py uvicorn ingress) with chunked streaming for
generator responses.
"""

from __future__ import annotations

import random
import threading
import time

from .._private import aioloop as _aioloop
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from .controller import AutoscalingConfig

_app_lock = threading.Lock()
_routers: Dict[str, "_Router"] = {}
_http_server = None
_controller_handle = None


class OverloadError(RuntimeError):
    """A request was shed by admission control (deployment queue bound
    or SLO router).  Retriable: the service is healthy but saturated —
    back off and resend instead of treating it as a failure."""

    retriable = True


@dataclass
class Deployment:
    cls_or_fn: Any
    name: str
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    num_cpus: float = 0.0
    num_tpus: int = 0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    # Queue-depth autoscaling (reference: serve/autoscaling_policy.py);
    # None = fixed num_replicas.
    autoscaling_config: Optional["AutoscalingConfig"] = None
    # Admission bound on the handle path: reject (OverloadError) once
    # in-flight requests exceed replica capacity (num_replicas *
    # max_ongoing_requests) plus this queue allowance.  None = queue
    # unboundedly (legacy behavior).
    max_queued_requests: Optional[int] = None

    def options(self, **kw) -> "Deployment":
        import dataclasses
        known = {f.name for f in dataclasses.fields(Deployment)}
        return dataclasses.replace(
            self, **{k: v for k, v in kw.items() if k in known})

    def bind(self, *args, **kwargs) -> "Application":
        import dataclasses
        d = dataclasses.replace(self, init_args=args, init_kwargs=kwargs)
        return Application(d)


@dataclass
class Application:
    deployment: Deployment


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 8,
               num_cpus: float = 0.0, num_tpus: int = 0,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               autoscaling_config: Optional["AutoscalingConfig"] = None,
               max_queued_requests: Optional[int] = None):
    """@serve.deployment (reference: serve/api.py:471)."""
    def wrap(cls):
        return Deployment(cls, name or cls.__name__,
                          num_replicas=num_replicas,
                          max_ongoing_requests=max_ongoing_requests,
                          num_cpus=num_cpus, num_tpus=num_tpus,
                          ray_actor_options=ray_actor_options or {},
                          autoscaling_config=autoscaling_config,
                          max_queued_requests=max_queued_requests)
    if _cls is not None:
        return wrap(_cls)
    return wrap


class _ReplicaActor:
    """Hosts the user callable (reference: replica.py UserCallableWrapper)."""

    def __init__(self, cls_blob: bytes, init_args, init_kwargs):
        from .._private import serialization
        target = serialization.loads_control(cls_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target

    def _resolve_target(self, method: str):
        target = getattr(self._callable, method, None)
        if target is None and method == "__call__":
            target = self._callable
        if target is None:
            raise AttributeError(f"deployment has no method {method!r}")
        return target

    def handle_request(self, method: str, args, kwargs,
                       multiplexed_model_id: Optional[str] = None):
        target = self._resolve_target(method)
        if multiplexed_model_id is None:
            return target(*args, **kwargs)
        # Multiplexed request: expose the model id for the duration of the
        # call (reference: serve.get_multiplexed_model_id()).
        from .multiplex import _set_current_model_id
        token = _set_current_model_id(multiplexed_model_id)
        try:
            return target(*args, **kwargs)
        finally:
            from .multiplex import _current_model_id
            _current_model_id.reset(token)

    def ping(self):
        return "ok"

    def handle_request_stream(self, method: str, args, kwargs,
                              multiplexed_model_id: Optional[str] = None):
        """Generator entry point: runs as a streaming actor call — each
        yielded item publishes immediately (token streaming).  Must BE a
        generator (not return one) so the multiplexed-model context stays
        installed while the body executes, not just until first return."""
        target = self._resolve_target(method)
        if multiplexed_model_id is None:
            yield from target(*args, **kwargs)
            return
        from .multiplex import _current_model_id, _set_current_model_id
        token = _set_current_model_id(multiplexed_model_id)
        try:
            yield from target(*args, **kwargs)
        finally:
            _current_model_id.reset(token)


class _DeploymentState:
    """Replica set + router state; mutated only by start/stop and the
    ServeController's reconcile loop (self-healing + autoscaling)."""

    def __init__(self, dep: Deployment):
        self.deployment = dep
        self.replicas: List[Any] = []
        self.inflight: Dict[int, int] = {}  # id(replica) -> in-flight count
        self.stopped = False
        # Reconcile-backfill crash-loop backoff (controller-owned).
        self.backfill_not_before = 0.0
        self.backfill_backoff_s = 0.5
        ac = dep.autoscaling_config
        self.target_replicas = max(dep.num_replicas, ac.min_replicas) \
            if ac is not None else dep.num_replicas
        from .multiplex import _MultiplexedDescriptor
        # Mirror the replica LRU size so routers stop preferring a
        # replica once it would have evicted the model (avoids reload
        # thrash pinning all hot models to one replica); shipped to
        # routers in the replica-set snapshot.
        cap = None
        target = dep.cls_or_fn
        if isinstance(target, type):
            for klass in target.__mro__:  # loaders may be inherited
                for attr in vars(klass).values():
                    if isinstance(attr, _MultiplexedDescriptor):
                        cap = attr._max
                        break
                if cap is not None:
                    break
        self.multiplex_cap = cap if cap is not None else 8
        self._lock = threading.Lock()
        self._opts: Optional[Dict[str, Any]] = None
        self._cls_blob: Optional[bytes] = None

    def _replica_opts(self):
        from .._private import serialization
        if self._opts is None:
            self._cls_blob = serialization.dumps_control(
                self.deployment.cls_or_fn)
            opts: Dict[str, Any] = {
                "max_concurrency": self.deployment.max_ongoing_requests,
                "num_cpus": self.deployment.num_cpus,
            }
            if self.deployment.num_tpus:
                opts["num_tpus"] = self.deployment.num_tpus
            opts.update(self.deployment.ray_actor_options)
            self._opts = opts
        return self._cls_blob, self._opts

    def add_replica(self, wait_ready: bool = False):
        import ray_tpu
        # Safe bare read: stopped is a monotonic shutdown latch; a stale
        # False only delays the error to the actor-create round trip.
        if self.stopped:  # ray-tpu: noqa[RT401]
            raise RuntimeError("deployment is stopped")
        cls_blob, opts = self._replica_opts()
        actor_cls = ray_tpu.remote(_ReplicaActor)
        r = actor_cls.options(**opts).remote(
            cls_blob, self.deployment.init_args, self.deployment.init_kwargs)
        if wait_ready:
            try:
                ray_tpu.get(r.ping.remote(), timeout=120)
            except Exception:
                ray_tpu.kill(r)
                raise
        with self._lock:
            if self.stopped:
                ray_tpu.kill(r)
                raise RuntimeError("deployment is stopped")
            self.replicas.append(r)
            self.inflight[id(r)] = 0
        return r

    def pop_replica(self, min_load: Optional[Dict[str, int]] = None,
                    specific=None):
        """Detach and return a replica WITHOUT killing it — the
        controller drains it first.  Default pick: least-loaded (by the
        router-reported per-replica loads); ``specific`` detaches that
        exact replica instead (node-drain evacuation)."""
        with self._lock:
            if not self.replicas:
                return None
            if specific is not None:
                if specific not in self.replicas:
                    return None  # already detached (double-drain race)
                idx = self.replicas.index(specific)
            else:
                loads = min_load or {}
                idx = min(range(len(self.replicas)),
                          key=lambda i: loads.get(
                              self.replicas[i]._actor_id.hex(), 0))
            r = self.replicas.pop(idx)
            self.inflight.pop(id(r), None)
            return r

    def start(self):
        import ray_tpu
        refs = [self.add_replica().ping.remote()
                for _ in range(self.target_replicas)]
        ray_tpu.get(refs, timeout=120)

    def stop(self):
        import ray_tpu
        with self._lock:
            self.stopped = True
            replicas, self.replicas = self.replicas, []
            self.inflight.clear()
        for r in replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


def _rt_token() -> int:
    from .._private import runtime as rtmod
    return id(rtmod.current_runtime())


def _cached_controller() -> Optional[Any]:
    """Cached handle, valid only for the CURRENT runtime (a new init()
    after shutdown must not reuse a dead cluster's controller)."""
    with _app_lock:
        if _controller_handle is not None and \
                _controller_handle[0] == _rt_token():
            return _controller_handle[1]
    return None


def _controller() -> Any:
    """Get-or-create the cluster's SERVE_CONTROLLER actor handle."""
    global _controller_handle
    import ray_tpu
    cached = _cached_controller()
    if cached is not None:
        return cached
    from .controller import (CONTROLLER_NAME, CONTROLLER_NAMESPACE,
                             ServeControllerActor)
    # Session-lifetime by design: deployments keep serving after the
    # driver's handles are gone — declare it to the leak sanitizer.
    from .._private import sanitizer
    sanitizer.session_scoped(CONTROLLER_NAME)
    cls = ray_tpu.remote(ServeControllerActor)
    last_exc: Optional[Exception] = None
    for _attempt in range(10):
        handle = cls.options(
            name=CONTROLLER_NAME, namespace=CONTROLLER_NAMESPACE,
            get_if_exists=True, max_restarts=10, num_cpus=0,
            max_concurrency=16).remote()
        try:
            ray_tpu.get(handle.ping.remote(), timeout=120)
        except Exception as e:  # noqa: BLE001
            # A dying controller (shutdown race) can win the name lookup;
            # wait for its death to land, then create fresh.
            last_exc = e
            time.sleep(0.3)
            continue
        with _app_lock:
            _controller_handle = (_rt_token(), handle)
        return handle
    raise RuntimeError(
        f"could not reach or recreate the serve controller: {last_exc!r}")


def _existing_controller() -> Optional[Any]:
    global _controller_handle
    cached = _cached_controller()
    if cached is not None:
        return cached
    import ray_tpu
    from .controller import CONTROLLER_NAME, CONTROLLER_NAMESPACE
    try:
        handle = ray_tpu.get_actor(CONTROLLER_NAME,
                                   namespace=CONTROLLER_NAMESPACE)
    except ValueError:
        return None
    with _app_lock:
        _controller_handle = (_rt_token(), handle)
    return handle


class _Router:
    """Per-process replica-set cache + pow-2 routing over LOCAL in-flight
    counts (reference: pow_2_router.py — routers track their own counts;
    the controller aggregates pushed totals for autoscaling)."""

    REFRESH_S = 1.0

    def __init__(self, name: str):
        import os
        self.name = name
        self.router_id = os.urandom(8).hex()
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[tuple] = []  # (actor_id_hex, handle)
        self._inflight: Dict[str, int] = {}
        self._fetched = 0.0
        # Admission state from the KV snapshot: total replica capacity
        # (sum of max_ongoing) and the deployment's queue allowance
        # (None = unbounded, the legacy behavior).
        self._capacity = 0
        self._max_queued: Optional[int] = None
        from .multiplex import RouterAffinity
        self.affinity = RouterAffinity(8)
        self._metrics_started = False
        # Driver-local fast path: evict replicas the moment the controller
        # marks their actor DEAD (reference: router reacting to
        # long-poll replica-set pushes) — the KV TTL refresh alone leaves
        # a window where fresh requests route to a corpse.
        import weakref

        from .._private import runtime as rtmod
        rt = rtmod.current_runtime()
        if rt is not None and hasattr(rt, "controller"):
            self_ref = weakref.ref(self)

            def on_actor_state(msg, _ref=self_ref):
                router = _ref()
                if router is None:
                    return
                actor_id, state = msg
                if state == "DEAD":
                    router.evict(actor_id.hex())
            rt.controller.subscribe("actor_state", on_actor_state)

    def evict(self, hexid: str) -> None:
        with self._lock:
            before = len(self._replicas)
            self._replicas = [e for e in self._replicas if e[0] != hexid]
            if len(self._replicas) != before:
                self._inflight.pop(hexid, None)
                self.affinity.drop_replica(hexid)
                # Force the next pick to consult the KV snapshot.
                self._fetched = 0.0

    def _refresh(self, force: bool = False) -> None:
        import pickle
        now = time.monotonic()
        with self._lock:
            if not force and now - self._fetched < self.REFRESH_S:
                return
        from .._private.api import _control
        from .controller import REPLICA_KV_PREFIX
        blob = _control("kv_get", REPLICA_KV_PREFIX + self.name)
        entries: List[tuple] = []
        version = None
        cap = None
        max_queued = None
        if blob is not None:
            snap = pickle.loads(blob)
            version, entries = snap[0], snap[1]
            if len(snap) > 2:
                cap = snap[2]
            if len(snap) > 3:
                max_queued = snap[3]
        with self._lock:
            self._fetched = now
            if version is None or version == self._version:
                if blob is None:
                    self._replicas = []
                return
            self._version = version
            self._capacity = sum(e[2] for e in entries)
            self._max_queued = max_queued
            if cap is not None and cap != self.affinity._max:
                from .multiplex import RouterAffinity
                self.affinity = RouterAffinity(cap)
            from .._private.api import ActorHandle
            from .._private.ids import ActorID
            live = set()
            handles = []
            for hexid, cls_name, max_ongoing in entries:
                live.add(hexid)
                handles.append((hexid, ActorHandle(
                    ActorID(bytes.fromhex(hexid)), cls_name)))
            self._replicas = handles
            for gone in set(self._inflight) - live:
                self._inflight.pop(gone, None)
                self.affinity.drop_replica(gone)

    def pick(self, model_id: Optional[str]) -> Optional[tuple]:
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return None
            if model_id is not None and n > 1:
                affine = set(self.affinity.replicas_for(model_id))
                cands = [e for e in self._replicas if e[0] in affine]
                if cands:
                    return min(cands, key=lambda e:
                               self._inflight.get(e[0], 0))
            if n == 1:
                return self._replicas[0]
            ia, ib = random.sample(range(n), 2)
            a, b = self._replicas[ia], self._replicas[ib]
            return a if self._inflight.get(a[0], 0) <= \
                self._inflight.get(b[0], 0) else b

    def note_start(self, hexid: str) -> None:
        with self._lock:
            self._inflight[hexid] = self._inflight.get(hexid, 0) + 1
            # Under the lock: an out-of-order set after release could
            # leave a stale in-flight count on a quiescent deployment.
            self._set_ongoing_gauge(sum(self._inflight.values()))
        self._ensure_metrics_thread()

    def note_done(self, hexid: str) -> None:
        with self._lock:
            if hexid in self._inflight:
                self._inflight[hexid] = max(0, self._inflight[hexid] - 1)
            self._set_ongoing_gauge(sum(self._inflight.values()))

    def _set_ongoing_gauge(self, total: int) -> None:
        from ..util import telemetry
        telemetry.set_gauge("ray_tpu_serve_ongoing_requests", total,
                            tags={"deployment": self.name})

    def total_inflight(self) -> int:
        with self._lock:
            return sum(self._inflight.values())

    def over_admission_bound(self) -> bool:
        """True when this router's in-flight count exceeds replica
        capacity plus the deployment's max_queued_requests allowance —
        the handle sheds instead of queueing unboundedly."""
        with self._lock:
            if self._max_queued is None or not self._replicas:
                return False
            return sum(self._inflight.values()) >= \
                self._capacity + self._max_queued

    def _ensure_metrics_thread(self) -> None:
        with self._lock:
            if self._metrics_started:
                return
            self._metrics_started = True

        def push():
            try:
                while True:
                    time.sleep(1.0)
                    with _app_lock:
                        if _routers.get(self.name) is not self:
                            return  # router replaced (redeploy): retire
                    from .._private import runtime as rtmod
                    if rtmod.current_runtime() is None:
                        return  # runtime shut down
                    try:
                        ctrl = _existing_controller()
                        if ctrl is None:
                            continue  # controller restarting: keep trying
                        with self._lock:
                            counts = {k: v
                                      for k, v in self._inflight.items()
                                      if v}
                        # Best-effort stats push; a lost tick is
                        # replaced by the next one.
                        ctrl.report_metrics.remote(  # ray-tpu: detached
                            self.name, self.router_id, counts)
                    except Exception:
                        # Transient (controller swap, runtime teardown
                        # race): retry next tick; the loop exits via the
                        # runtime/router checks above.
                        continue
            finally:
                # Let a future request respawn the pusher if this router
                # is still the live one (a dead pusher would silently
                # starve the autoscaler and mis-drain downscales).
                with self._lock:
                    self._metrics_started = False
        from .._private import sanitizer
        sanitizer.spawn(push, name=f"serve-metrics-{self.name}")


def _router_for(name: str) -> _Router:
    with _app_lock:
        r = _routers.get(name)
        if r is None:
            r = _routers[name] = _Router(name)
    return r


class DeploymentHandle:
    """reference: serve/handle.py:1041 — .remote() routes a request;
    ``options(stream=True)`` returns an ObjectRefGenerator over a
    generator method's yielded items (token streaming)."""

    def __init__(self, name: str, method: str = "__call__",
                 multiplexed_model_id: Optional[str] = None,
                 stream: bool = False):
        self._name = name
        self._method = method
        self._model_id = multiplexed_model_id
        self._stream = stream

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(self._name, method_name or self._method,
                                multiplexed_model_id or self._model_id,
                                self._stream if stream is None else stream)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentHandle(self._name, item, self._model_id,
                                self._stream)

    def remote(self, *args, **kwargs):
        from ..util import telemetry, tracing
        t_route = time.perf_counter()
        t_route_wall = time.time()
        tags = {"deployment": self._name}

        def _note_latency():
            telemetry.observe("ray_tpu_serve_request_latency_seconds",
                              time.perf_counter() - t_route, tags=tags)

        router = _router_for(self._name)
        router._refresh()
        if router.over_admission_bound():
            # SLO-aware shedding: overload degrades into a fast
            # retriable rejection, not a queue that times out later.
            telemetry.inc("ray_tpu_serve_shed_total", tags=tags)
            raise OverloadError(
                f"deployment {self._name!r} is over its admission bound "
                "(max_queued_requests); retry with backoff")
        # A reconcile may briefly leave zero replicas (all died at once);
        # wait for the controller to backfill rather than failing the
        # request (reference: router retries against the long-poll set).
        deadline = time.monotonic() + 60
        while True:
            picked = router.pick(self._model_id)
            if picked is not None:
                break
            if time.monotonic() > deadline:
                telemetry.inc("ray_tpu_serve_request_errors_total",
                              tags=tags)
                raise RuntimeError(
                    f"deployment {self._name!r} has no live replicas")
            time.sleep(0.05)
            router._refresh(force=True)
        hexid, replica = picked
        # Handle-path queue wait as a trace span: admission + replica
        # pick, parented under the caller's context — and installed as
        # the parent of the actor submit below, so the whole request
        # (route -> submit -> execute -> engine phases) is ONE tree even
        # when the caller had no ambient context.
        route_ctx = tracing.record_span(
            tracing.current(), f"serve_route {self._name}",
            t_route_wall, t_route_wall + (time.perf_counter() - t_route),
            {"deployment": self._name, "replica": hexid[:12]})
        telemetry.inc("ray_tpu_serve_requests_total", tags=tags)
        router.note_start(hexid)
        if self._model_id is not None:
            router.affinity.note(hexid, self._model_id)
        method = "handle_request_stream" if self._stream \
            else "handle_request"
        submit = getattr(replica, method)
        if self._stream:
            submit = submit.options(num_returns="streaming")
        prev_ctx = tracing.current()
        if route_ctx is not None:
            tracing.set_current(route_ctx)
        try:
            if self._model_id is not None:
                ref = submit.remote(self._method, args, kwargs,
                                    multiplexed_model_id=self._model_id)
            else:
                ref = submit.remote(self._method, args, kwargs)
        finally:
            if route_ctx is not None:
                tracing.set_current(prev_ctx)
        if self._stream:
            # Streamed request: the wrapper decrements in-flight when the
            # consumer finishes (or abandons) the stream.
            def _stream_refs(gen=ref):
                try:
                    for item_ref in gen:
                        yield item_ref
                finally:
                    router.note_done(hexid)
                    _note_latency()
            return _stream_refs()

        def _done():
            _wait_quiet(ref)
            router.note_done(hexid)
            _note_latency()
        # Decrement when the result materializes.
        from .._private import sanitizer
        sanitizer.spawn(_done, name="serve-done-watch")
        return ref


def _wait_quiet(ref):
    import ray_tpu
    try:
        ray_tpu.wait([ref], num_returns=1, timeout=3600)
    except Exception:
        pass


def run(app: Application, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None,
        http_port: Optional[int] = None) -> DeploymentHandle:
    """Deploy through the controller actor and return a handle
    (reference: serve/api.py:902).  The controller owns the replicas, so
    the deployment keeps serving if this driver exits."""
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    dep = app.deployment if isinstance(app, Application) else app
    from .._private import serialization
    ctrl = _controller()
    ray_tpu.get(ctrl.deploy.remote(serialization.dumps_control(dep)),
                timeout=300)
    with _app_lock:
        _routers.pop(dep.name, None)  # drop stale replica cache
    if http_port is not None:
        _ensure_http(http_port)
    return DeploymentHandle(dep.name)


def get_deployment_handle(name: str) -> DeploymentHandle:
    import pickle

    from .._private.api import _control
    from .controller import REPLICA_KV_PREFIX
    if _control("kv_get", REPLICA_KV_PREFIX + name) is None:
        raise ValueError(f"no deployment named {name!r}")
    _ = pickle  # (snapshot validated lazily by the router)
    return DeploymentHandle(name)


def status() -> Dict[str, Dict[str, Any]]:
    import ray_tpu
    ctrl = _existing_controller()
    if ctrl is None:
        return {}
    return ray_tpu.get(ctrl.status.remote(), timeout=60)


def shutdown() -> None:
    """Stop every deployment and the controller actor (reference:
    serve.shutdown tearing down the Serve instance)."""
    global _http_server, _controller_handle
    import ray_tpu
    ctrl = _existing_controller()
    if ctrl is not None:
        try:
            ray_tpu.get(ctrl.shutdown_all.remote(), timeout=120)
        except Exception:
            pass
        try:
            ray_tpu.kill(ctrl)
        except Exception:
            pass
    with _app_lock:
        _controller_handle = None
        _routers.clear()
    if _http_server is not None:
        _http_server.stop()
        _http_server = None


# --------------------------------------------------------------------- #
# HTTP ingress (reference: _private/proxy.py; aiohttp instead of uvicorn)
# --------------------------------------------------------------------- #

def build_ingress_app():
    """The ingress aiohttp application: POST /{deployment} routes the
    JSON body through a deployment handle (chunked ndjson when
    ``stream`` is set).  Shared by the in-process _HttpServer and the
    per-node ProxyActor (serve/proxy.py)."""
    import asyncio

    from aiohttp import web

    async def handle(request: "web.Request"):
            import json as _json
            name = request.match_info["deployment"]
            try:
                body = await request.json()
            except Exception:
                body = {}
            stream = bool(body.pop("stream", False)) if isinstance(
                body, dict) else False
            try:
                handle_ = get_deployment_handle(name)
                import ray_tpu
                loop = asyncio.get_event_loop()
                if stream:
                    # Chunked streaming ingress (reference: proxy.py
                    # streaming responses): each generator item is one
                    # newline-delimited JSON chunk.
                    gen = handle_.options(stream=True).remote(body)
                    resp = web.StreamResponse(headers={
                        "Content-Type": "application/x-ndjson"})
                    await resp.prepare(request)
                    it = iter(gen)
                    try:
                        while True:
                            item_ref = await loop.run_in_executor(
                                None, lambda: next(it, None))
                            if item_ref is None:
                                break
                            item = await loop.run_in_executor(
                                None, lambda: ray_tpu.get(item_ref,
                                                          timeout=300))
                            await resp.write(
                                (_json.dumps({"result": item})
                                 + "\n").encode())
                    except Exception as e:  # noqa: BLE001
                        # Mid-stream failure: the chunked response is
                        # already prepared — emit an error CHUNK, never a
                        # second response.
                        await resp.write(
                            (_json.dumps({"error": repr(e)})
                             + "\n").encode())
                    await resp.write_eof()
                    return resp
                try:
                    ref = handle_.remote(body)
                except Exception as e:  # noqa: BLE001
                    # Handle-level failure (e.g. no live replicas):
                    # remote() already counted it — don't double-count.
                    return web.json_response({"error": repr(e)},
                                             status=500)
                result = await loop.run_in_executor(
                    None, lambda: ray_tpu.get(ref, timeout=300))
                return web.json_response({"result": result})
            except Exception as e:  # noqa: BLE001
                from ..util import telemetry
                telemetry.inc("ray_tpu_serve_request_errors_total",
                              tags={"deployment": name})
                return web.json_response({"error": repr(e)}, status=500)

    app = web.Application()
    app.router.add_post("/{deployment}", handle)
    app.router.add_get("/-/healthz",
                       lambda r: web.Response(text="ok"))
    return app


class _HttpServer:
    def __init__(self, port: int, host: str = "127.0.0.1"):
        self.port = port
        self.host = host
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._started = threading.Event()
        self._runner = None
        self._loop = None
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("serve http ingress failed to start")

    def _serve(self):
        import asyncio

        from aiohttp import web

        async def main():
            app = build_ingress_app()
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            try:
                # Ephemeral bind (port 0): record the real port.
                self.port = site._server.sockets[0].getsockname()[1]
            except Exception:
                pass
            self._runner = runner
            self._started.set()
            while True:
                await asyncio.sleep(3600)

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(main())
        except Exception:
            pass
        finally:
            # Executor + loop retirement shared across the three
            # daemon-loop servers (see _private/aioloop.py).
            _aioloop.shutdown_loop(self._loop)

    def stop(self):
        _aioloop.stop_loop_thread(self._loop, self._thread)


def _ensure_http(port: int) -> None:
    global _http_server
    if _http_server is None:
        _http_server = _HttpServer(port)
