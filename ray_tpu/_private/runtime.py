"""Driver-side runtime: ownership, object directory, task routing, actors.

This is the CoreWorker-equivalent for the driver process (reference:
src/ray/core_worker/core_worker.h:167) plus the pieces of the reference's
TaskManager / ReferenceCounter / ActorTaskSubmitter that round-1 centralizes
in the driver:

  * ObjectDirectory — per-object state + waiters (reference: memory store
    futures, store_provider/memory_store/).
  * submission routing — normal tasks go through the cluster scheduler
    (dependency stage + placement, reference: normal_task_submitter.h:86);
    actor tasks are sequenced per-actor and pushed to the actor's dedicated
    worker (reference: actor_task_submitter.h:68 SequentialActorSubmitQueue).
  * failure handling — task retries on worker crash (reference:
    task_manager.h:248 ResubmitTask), actor restart FSM driven off worker
    death (reference: gcs_actor_manager.h:94).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import sanitizer
from . import serialization
from . import wire as _wire
from .config import Config
from .events import (FAILED, FINISHED, PENDING_ARGS, PLACED, READY, RUNNING,
                     SUBMITTED_TO_NODE, ProfileSpan, TaskEventBuffer)
from .controller import (ALIVE, DEAD, PENDING_CREATION, PG_PENDING,
                         PG_REMOVED, RESTARTING, ActorInfo, Controller,
                         JobInfo, NodeInfo, PlacementGroupInfo)
from .exceptions import (ActorError, GetTimeoutError, ObjectLostError,
                         OutOfMemoryError, TaskError, WorkerCrashedError)
from .ids import (ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID,
                  WorkerID)
from .node import NodeManager
from .object_store import RemoteObjectReader
from ..storeview import events as _store_events
from .protocol import (ActorStateMsg, GetReply, GetRequest, PutFromWorker,
                       RpcCall, RpcReply, TaskDone, TaskSpec, WaitReply,
                       WaitRequest)
from .resources import CPU, TPU, ResourceSet
from .scheduler import ClusterScheduler
from ..util import telemetry

_runtime_lock = threading.Lock()
_global_runtime: Optional["Runtime"] = None
_worker_runtime = None  # set in worker processes


def set_worker_runtime(rt) -> None:
    global _worker_runtime
    _worker_runtime = rt


def current_runtime():
    """The active runtime facade: WorkerRuntime inside workers, else driver."""
    if _worker_runtime is not None:
        return _worker_runtime
    return _global_runtime


def driver_runtime() -> Optional["Runtime"]:
    return _global_runtime


class ObjectState:
    """One object-directory entry.  The direct-call fast path creates
    tens of thousands per second, so construction must be
    allocation-light: the real threading.Event (whose Condition is the
    single most expensive allocation on the submit path) is created
    lazily, only when a consumer blocks before the result lands.
    ``ready`` is a plain bool flipped under the class-wide lock; readers
    may peek it unlocked (GIL write-once visibility — the same guarantee
    Event.is_set() gave).  The shared lock is fine: every critical
    section is O(1) and tiny."""

    __slots__ = ("ready", "desc", "callbacks", "_evt")
    _lock = threading.Lock()

    def __init__(self):
        self.ready = False
        self.desc = None
        self.callbacks: Optional[List[Callable[[], None]]] = None
        self._evt: Optional[threading.Event] = None

    def mark_ready(self, desc) -> None:
        with ObjectState._lock:
            if self.ready:
                return
            self.desc = desc
            self.ready = True
            evt = self._evt
            cbs, self.callbacks = self.callbacks, None
        if evt is not None:
            evt.set()
        for cb in cbs or ():
            cb()

    def reset(self) -> None:
        """Back to pending (object lost; reconstruction in flight) so
        consumers block until the re-executed task delivers."""
        with ObjectState._lock:
            self.desc = None
            self.ready = False
            if self._evt is not None:
                self._evt.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        # Safe bare read: double-checked fast path — ready only flips
        # under the class lock, and we re-check under it below.
        if self.ready:  # ray-tpu: noqa[RT401]
            return True
        with ObjectState._lock:
            if self.ready:
                return True
            evt = self._evt
            if evt is None:
                evt = self._evt = threading.Event()
        return evt.wait(timeout)

    def add_callback(self, cb: Callable[[], None]) -> None:
        with ObjectState._lock:
            if not self.ready:
                if self.callbacks is None:
                    self.callbacks = []
                self.callbacks.append(cb)
                return
        cb()

    def discard_callback(self, cb: Callable[[], None]) -> None:
        with ObjectState._lock:
            if self.callbacks:
                try:
                    self.callbacks.remove(cb)
                except ValueError:
                    pass


def _has_remote_desc(args, kwargs) -> bool:
    return any(isinstance(d, tuple) and d and d[0] == "at"
               for d in list(args) + list(kwargs.values()))


class _DepsPending(Exception):
    """A dependency's descriptor vanished (object lost; reconstruction in
    flight) between scheduling and dispatch."""

    def __init__(self, oids):
        self.oids = oids
        super().__init__(f"{len(oids)} dependencies back to pending")


@dataclass
class _RunningTask:
    spec: TaskSpec
    node_id: NodeID
    worker_id: Optional[WorkerID] = None


@dataclass
class _ActorRuntimeState:
    worker_id: Optional[WorkerID] = None
    node_id: Optional[NodeID] = None
    next_seq: int = 0          # next sequence number to assign
    next_dispatch: int = 0     # next sequence number eligible to dispatch
    ready_buffer: Dict[int, Tuple[TaskSpec, list, dict]] = field(default_factory=dict)
    pending_bind: List[Tuple[TaskSpec, list, dict]] = field(default_factory=list)
    lock: threading.RLock = field(default_factory=threading.RLock)
    # Direct-call listener of the actor's worker (direct.py); set on the
    # worker's "alive" report, cleared on worker death.
    direct_addr: Optional[Tuple[str, int]] = None
    # Driver->actor direct channel (cluster mode).  driver_mode flips to
    # "direct" (sticky) the first time a fast-path call finds the actor
    # quiescent — no queued/unbound calls AND no classic dispatches still
    # in flight — so a channel frame can never overtake a classic one.
    driver_mode: Optional[str] = None
    driver_ch: Any = None
    classic_inflight: set = field(default_factory=set)


class _DriverChannelOwner:
    """DirectChannel owner shim for the driver Runtime: actor resolution
    goes straight to the controller; channel replies land in the driver's
    object directory (local_ready -> mark_ready).  Non-inline results
    arrive upstream as a normal TaskDone from the actor's node — which
    registers and marks them ready — so the channel's "upstream" signal
    is a no-op here."""

    def __init__(self, rt):
        self.rt = rt
        self.direct_token = rt.node.direct_token

    def control(self, method: str, *args):
        return getattr(self.rt, "ctl_" + method)(*args)

    def local_ready(self, oid_bytes: bytes, desc) -> None:
        if desc and desc[0] == "upstream":
            return
        self.rt.mark_ready(ObjectID(oid_bytes), desc)


class Runtime:
    """Driver-process runtime (controller + scheduler + local node plane)."""

    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 namespace: str = "default",
                 head_port: Optional[int] = None,
                 cluster_token: Optional[bytes] = None,
                 advertise_host: Optional[str] = None,
                 state_dir: Optional[str] = None):
        Config.initialize()
        self.controller = Controller()
        self.state_store = None
        if state_dir:
            # Head fault tolerance: replay persisted controller tables
            # before anything registers (reference: gcs_server.cc loading
            # GcsInitData on boot), then attach the WAL for new mutations.
            from .persist import StateStore
            store = StateStore(state_dir,
                               fsync=bool(Config.get("head_wal_fsync")))
            self.controller.restore(store.load())
            self.controller.persist = store
            store.on_compact = lambda: store.compact(
                self.controller.snapshot_records())
            self.state_store = store
            # Job counter must advance past replayed jobs or the new
            # driver job collides with a restored one.
            import struct as _struct
            with JobID._lock:
                for j in self.controller.jobs:
                    (val,) = _struct.unpack("<I", j.binary())
                    JobID._counter = max(JobID._counter, val)
        self.job_id = JobID.next()
        self.namespace = namespace
        self.driver_task_id = TaskID.for_driver(self.job_id)
        self.controller.register_job(JobInfo(self.job_id))

        if num_tpus is None:
            from ..accelerators.tpu import TPUAcceleratorManager
            num_tpus = TPUAcceleratorManager.detect_num_chips()
        node_resources: Dict[str, float] = {
            CPU: float(num_cpus if num_cpus is not None else (os.cpu_count() or 1)),
            "memory": float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
            if hasattr(os, "sysconf") else 64e9,
        }
        if num_tpus:
            node_resources[TPU] = float(num_tpus)
            from ..accelerators.tpu import TPUAcceleratorManager
            marker = TPUAcceleratorManager.slice_head_resource_name()
            if marker:
                node_resources[marker] = 1.0
        # Other registered accelerator plugins advertise their chips too
        # (reference: the per-vendor manager loop in
        # _private/accelerators/__init__.py).
        from ..accelerators.accelerator import all_accelerators
        for mgr in all_accelerators():
            if mgr.resource_name in node_resources:
                continue
            try:
                n = mgr.detect_num_chips()
            except Exception:
                n = 0
            if n:
                node_resources[mgr.resource_name] = float(n)
        if resources:
            node_resources.update(resources)

        self.node_id = NodeID.from_random()
        node_info = NodeInfo(self.node_id, socket.gethostname(),
                             ResourceSet(node_resources), is_head=True)
        self.controller.register_node(node_info)

        self.directory: Dict[ObjectID, ObjectState] = {}
        self._dir_lock = threading.RLock()
        self._mapped_segments: Dict[ObjectID, Any] = {}
        # Arena objects pinned on behalf of driver-held zero-copy views;
        # released at free() (plasma client-pin semantics).
        self._arena_pins: set = set()

        # -- ownership / GC (reference: reference_counter.h:44) ----------- #
        # Driver-process ObjectRef counts; objects with zero refs, zero
        # in-flight dependent tasks and no escaped (pickled-away) copies
        # are freed from the directory + store.
        self._gc_enabled = bool(Config.get("enable_object_gc"))
        self._ref_lock = threading.Lock()
        # Zero-copy view tracking: materialized values alias shm/arena
        # memory, so a GC-triggered free must wait for the views to die
        # (plasma buffer-retention semantics).  Values that can't carry a
        # weakref keep their object pinned for the session (leak-safe).
        self._view_counts: Dict[ObjectID, int] = {}
        self._view_immortal: set = set()
        self._pending_free: set = set()
        # __del__ may fire at arbitrary GC points (possibly while this very
        # process holds _ref_lock), so ref drops are queued lock-free and
        # drained by a dedicated thread (reference: the Cython ObjectRef
        # dealloc defers to the io service for the same reason).
        import queue as _q
        self._ref_drop_q: Any = _q.SimpleQueue()
        if self._gc_enabled:
            sanitizer.spawn(self._ref_drop_loop, name="ref-gc")
        self._local_refs: Dict[ObjectID, int] = {}
        self._escaped: set = set()
        self._dropped: set = set()
        self._dep_counts: Dict[ObjectID, int] = {}
        self._deps_retained: Dict[TaskID, List[ObjectID]] = {}
        # outer object -> ObjectIDs serialized inside its value: the
        # inner objects are retained (via _dep_counts) for exactly the
        # outer's lifetime (reference: reference_counter.h:44 nested-ref
        # containment).
        self._contained: Dict[ObjectID, List[ObjectID]] = {}

        # -- lineage + reconstruction (reference: task_manager.h:248
        # ResubmitTask, object_recovery_manager.h:41) ---------------------- #
        from collections import OrderedDict
        self._lineage: "OrderedDict[TaskID, TaskSpec]" = OrderedDict()
        self._lineage_lock = threading.Lock()
        self._lineage_cap = int(Config.get("lineage_max_entries"))
        self._recovering: Dict[TaskID, threading.Event] = {}
        self._recover_attempts: Dict[TaskID, int] = {}

        # Session directory + worker-log tailing + export events
        # (reference: /tmp/ray/session_* with log_monitor.py:116 and
        # RayEventRecorder export events).  Created before the NodeManager
        # so the first spawned worker already redirects into it.
        from .log_monitor import (ExportEventWriter, LogMonitor,
                                  create_session_dir)
        self.session_dir = create_session_dir()
        self.session_logs_dir = os.path.join(self.session_dir, "logs")
        self.log_monitor = LogMonitor(self.session_logs_dir)
        self.log_monitor.start()
        self.export_events = ExportEventWriter(
            os.path.join(self.session_logs_dir, "events.jsonl"))
        self.controller.event_sink = self.export_events.write

        self.scheduler = ClusterScheduler(self.controller, self._object_ready)
        self.scheduler.on_dispatch_error = self._fail_task
        self.scheduler.try_pipeline = self._try_pipeline
        # Tasks queued ahead on a busy worker (pipelined submission):
        # they hold no resource booking, so TaskDone skips release.
        self._pipelined: set = set()
        # Per-node credit accounting for REMOTE pipelining (reference: the
        # C++ submitter's per-lease in-flight cap,
        # normal_task_submitter.cc:516): at most _pipeline_cap(node)
        # lease-less tasks ride ahead to each remote node; a credit
        # returns on TaskDone/failure/UpPipelineReject.
        self._pipeline_credits: Dict[NodeID, int] = {}
        self._pipelined_node: Dict[TaskID, NodeID] = {}
        self._pipeline_lock = threading.Lock()
        # node_id -> monotonic deadline: a node that just rejected a
        # pipelined dispatch is skipped until the deadline, so a full
        # pool doesn't ping-pong tasks head<->node (localizing args each
        # round trip) while nothing has changed.
        self._pipeline_cooldown: Dict[NodeID, float] = {}
        self.node = NodeManager(node_info, self, num_tpu_chips=int(num_tpus or 0))
        self.scheduler.add_node(node_info)
        self.nodes: Dict[NodeID, NodeManager] = {self.node_id: self.node}

        self._running: Dict[TaskID, _RunningTask] = {}
        self._running_lock = threading.Lock()
        # fn_id -> pickled function (reference: GCS function table).
        self._fn_table: Dict[bytes, bytes] = {}
        # Syncer receiver state: node -> (version, view, recv_time).
        self._node_views: Dict[NodeID, tuple] = {}
        self._node_views_lock = threading.Lock()
        self._actors: Dict[ActorID, _ActorRuntimeState] = {}
        self._actors_lock = threading.Lock()
        # Direct actor calls in flight (fast path, see submit_actor_direct):
        # task_id bytes -> (actor_id, return_ids, call_name).  These tasks
        # bypass the running table / events / scheduler entirely.
        self._direct_lock = threading.Lock()
        self._direct_inflight: Dict[
            bytes, Tuple[ActorID, List[ObjectID], str]] = {}
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._shutdown = False

        self.events = TaskEventBuffer(
            Config.get("task_events_max_num_task_in_gcs"))
        # Control-plane telescope: the scheduler folds READY/PLACED
        # lifecycle stamps into the TaskEvent ring (stage-wait
        # histograms derive from the per-transition monotonic stamps).
        self.scheduler.on_stage = self.events.record
        # worker_id hex -> latest user-metrics snapshot pushed from that
        # process (see ray_tpu.util.metrics).
        self.metrics_snapshots: Dict[str, list] = {}
        # Metrics time-series backplane: bounded history + windowed
        # queries + SLO burn-rate alerts, fed from the metrics_push
        # verb (no reporting loop of its own; see ray_tpu.metricsview).
        from ray_tpu.metricsview import MetricsView
        self.metricsview = MetricsView(event_sink=self._export_event)

        # -- live diagnostics (reference: `ray stack` + the debug-state
        # dump; see diagnostics.py) ------------------------------------- #
        # dump_id -> {"replies": {worker_hex: record}, "event", "want"}
        self._stack_lock = threading.Lock()
        self._stack_dump_seq = 0
        self._stack_dumps: Dict[int, Dict[str, Any]] = {}
        # profile_id -> same collection-entry shape as _stack_dumps
        # (cluster profiler shares the stack-capture fan-out/settle
        # machinery; see ctl_profile).
        self._profile_seq = 0
        self._profiles: Dict[int, Dict[str, Any]] = {}
        # Rate limiter for the worker-death flight recorder.
        # None = no bundle written yet (0.0 would suppress the first
        # bundle on a freshly booted host: monotonic ~= uptime).
        self._last_death_bundle: Optional[float] = None

        # -- multi-node cluster plane (reference: gcs_node_manager.h node
        # registration + object_manager pull/push; see cluster.py) -------- #
        self.head_server = None
        self.data_server = None
        self._data_client = None
        self._puller = None
        self._xfer_q = None
        if head_port is not None:
            import queue as _queue

            from .cluster import (DataClient, DataServer, HeadServer,
                                  ObjectPuller)
            # No silent well-known default: the control port unpickles peer
            # messages, so an unauthenticated join would be code execution.
            token = cluster_token or os.urandom(16)
            self.cluster_token = token
            # Direct channels must work across nodes: all workers in the
            # cluster share the cluster token and bind on routable hosts.
            self.node.direct_token = token
            self.node.direct_host = advertise_host or os.environ.get(
                "RAY_TPU_ADVERTISE_HOST", "127.0.0.1")
            advertise = advertise_host or os.environ.get(
                "RAY_TPU_ADVERTISE_HOST", "127.0.0.1")
            self.data_server = DataServer(self.node.store, token,
                                          advertise_host=advertise)
            self._data_client = DataClient(token)
            self.head_server = HeadServer(self, port=head_port, token=token,
                                          advertise_host=advertise)
            self._puller = ObjectPuller(
                self.node.store, self._data_client, self.node_id.binary(),
                self.head_server.node_data_address)
            # Cross-node pulls block; never run them on the scheduler loop
            # or a node reader thread (see _offload).  Ordered work (actor
            # dispatch) gets its own queue thread; everything else shares a
            # pool so one stalled peer can't freeze the data plane.
            from concurrent.futures import ThreadPoolExecutor
            self._xfer_q = _queue.Queue()
            self._xfer_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="head-xfer")
            sanitizer.spawn(self._xfer_loop, name="head-xfer-ordered")

        if self.state_store is not None:
            self._revive_persisted_state()

    def _revive_persisted_state(self) -> None:
        """After a head restart: re-plan replayed placement groups on the
        fresh node set and restart replayed actors from their creation
        specs (their workers died with the old head; restarting does NOT
        consume the user's restart budget — reference: GCS failover
        reconstructing actors from GcsInitData)."""
        for pg in list(self.controller.placement_groups.values()):
            if pg.state == PG_REMOVED:
                continue
            for b in pg.bundles:
                b.node_id = None
            pg.state = PG_PENDING
            self.scheduler.create_placement_group(pg)
        for info in list(self.controller.actors.values()):
            if info.state == DEAD or info.creation_spec is None:
                continue
            with self._actors_lock:
                self._actors[info.actor_id] = _ActorRuntimeState()
            self.controller.set_actor_state(info.actor_id, RESTARTING)
            self._submit_actor_creation(
                self._restart_creation_spec(info.actor_id,
                                            info.creation_spec))

    @staticmethod
    def _restart_creation_spec(actor_id: ActorID, spec: TaskSpec) -> TaskSpec:
        """Fresh creation TaskSpec for restarting an actor from its
        original creation spec (new task id; returns already delivered)."""
        return TaskSpec(
            task_id=TaskID.of(actor_id), name=spec.name,
            fn_blob=spec.fn_blob, method_name=None,
            arg_descs=spec.arg_descs, kwarg_descs=spec.kwarg_descs,
            return_ids=[], resources=spec.resources,
            create_actor_id=actor_id, max_retries=0,
            placement_group=spec.placement_group,
            bundle_index=spec.bundle_index,
            scheduling_strategy=spec.scheduling_strategy,
            runtime_env=spec.runtime_env,
            max_concurrency=spec.max_concurrency)

    # ------------------------------------------------------------------ #
    # object directory
    # ------------------------------------------------------------------ #

    def _state(self, object_id: ObjectID) -> ObjectState:
        with self._dir_lock:
            st = self.directory.get(object_id)
            if st is None:
                st = ObjectState()
                self.directory[object_id] = st
            return st

    def _object_ready(self, object_id: ObjectID) -> bool:
        with self._dir_lock:
            st = self.directory.get(object_id)
        return st is not None and st.ready

    def mark_ready(self, object_id: ObjectID, desc) -> None:
        self._state(object_id).mark_ready(desc)
        self.scheduler.notify_object_ready(object_id)
        if self._gc_enabled:
            # The ref was dropped while the producing task was in flight:
            # collect the result now that it has landed.  (The lock is
            # required for the check: an unlocked emptiness pre-check races
            # with the drop path's insert — drop reads event-unset, we set
            # it and see _dropped still empty, drop inserts -> leak.)
            with self._ref_lock:
                collect = object_id in self._dropped and \
                    self._collectable_locked(object_id)
                if collect:
                    self._dropped.discard(object_id)
            if collect:
                self.free([object_id])

    def _materialize(self, object_id: ObjectID, desc) -> Any:
        if desc[0] == "at":
            # Remote-node object: pull it into the head's local store first
            # (owner lookup + transfer, reference: pull_manager.h:50).
            if self._puller is None:
                raise ObjectLostError(
                    f"object {object_id} lives on a remote node but this "
                    "runtime has no cluster data plane")
            desc = self._puller.localize(desc)
        kind = desc[0]
        if kind == "inline":
            return serialization.unpack_payload(desc[1])
        if kind == "shm":
            shm = self._mapped_segments.get(object_id)
            if shm is None:
                try:
                    value, shm = RemoteObjectReader.read(desc[1], desc[2])
                    # The mapping read bypasses the store, so the lifecycle
                    # ring would count this object as never-read (and flag
                    # it as a leak candidate).  Record the read here; the
                    # restore fallback below goes through get_buffer, which
                    # records it itself.
                    ring = getattr(self.node.store, "view", None)
                    if ring is not None and _store_events.enabled():
                        ring.push(_store_events.E_GET,
                                  object_id.binary(), desc[2])
                except FileNotFoundError:
                    # The local store spilled this object: its segment
                    # was unlinked when the payload moved to disk.  A
                    # store read restores the segment under the same
                    # name, after which the mapping works again.
                    try:
                        buf, _keep = self.node.store.get_buffer(object_id)
                    except (KeyError, ValueError) as e:
                        raise ObjectLostError(
                            f"object {object_id} segment is gone and the "
                            f"local store cannot restore it: {e}",
                            object_id_bytes=object_id.binary()) from None
                    buf.release()
                    value, shm = RemoteObjectReader.read(desc[1], desc[2])
                self._mapped_segments[object_id] = shm
            else:
                value = serialization.read_payload_from(shm.buf[: desc[2]])
            self._track_view(object_id, value)
            return value
        if kind == "shma":
            # Pin once per driver-held object so the arena offset stays valid
            # for any zero-copy views the caller retains; released at free().
            pin = object_id not in self._arena_pins
            value = self.node.store.read_by_key(desc[4], pin=pin)
            if value is None:
                raise ObjectLostError(
                    f"object {object_id} was evicted or freed",
                    object_id_bytes=object_id.binary())
            if pin:
                self._arena_pins.add(object_id)
            self._track_view(object_id, value)
            return value
        if kind == "err":
            raise serialization.unpack_payload(desc[1])
        raise ValueError(f"bad descriptor {desc!r}")

    # ------------------------------------------------------------------ #
    # public API surface (driver side)
    # ------------------------------------------------------------------ #

    def put(self, value: Any) -> ObjectID:
        with self._put_lock:
            self._put_index += 1
            idx = (1 << 20) + self._put_index
        object_id = ObjectID.of(self.driver_task_id, idx)
        # Refs inside the value become containment-retained (released
        # when this object frees), not escaped-forever pins.
        from .api import _nested_collector
        inner: list = []
        token = _nested_collector.set(inner)
        try:
            meta, buffers = serialization.serialize_payload(value)
        finally:
            _nested_collector.reset(token)
        if inner:
            self.note_contained(object_id, inner)
        nbytes = serialization.payload_nbytes(meta, buffers)
        if nbytes <= Config.get("max_inline_object_size"):
            buf = bytearray(nbytes)
            serialization.write_payload_into(memoryview(buf), meta, buffers)
            self.mark_ready(object_id, ("inline", bytes(buf)))
        else:
            self.node.store.put_serialized(object_id, meta, buffers)
            self.mark_ready(object_id, self.node.store.descriptor(object_id))
        return object_id

    def _states(self, object_ids: List[ObjectID]) -> List[ObjectState]:
        """Bulk _state(): one directory-lock round for the whole list."""
        with self._dir_lock:
            directory = self.directory
            states = []
            for o in object_ids:
                st = directory.get(o)
                if st is None:
                    st = ObjectState()
                    directory[o] = st
                states.append(st)
            return states

    def get(self, object_ids: List[ObjectID],
            timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        states = self._states(object_ids)
        for st in states:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError("get timed out")
            if not st.wait(remaining):
                raise GetTimeoutError("get timed out")
        values = []
        max_attempts = int(Config.get("object_reconstruction_max_attempts"))
        for o, st in zip(object_ids, states):
            last: Optional[BaseException] = None
            for _attempt in range(max_attempts + 1):
                try:
                    values.append(self._materialize(o, st.desc))
                    last = None
                    break
                except ObjectLostError as e:
                    # Lost from the cluster: try lineage re-execution
                    # (reference: object_recovery_manager.h:92).
                    last = e
                    if self._recover_object(o) is None:
                        raise
                    remaining = None if deadline is None else \
                        deadline - time.monotonic()
                    if not st.wait(remaining):
                        raise GetTimeoutError(
                            "get timed out during object reconstruction")
            if last is not None:
                raise last
        return values

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        """Event-driven wait: readiness callbacks signal a condition — no
        poll loop (the round-1 1ms spin showed up directly in the wait_1k
        microbenchmark; reference: WaitManager wait_manager.h)."""
        if num_returns > len(object_ids):
            raise ValueError(
                f"num_returns={num_returns} exceeds the {len(object_ids)} "
                "refs passed to wait()")
        deadline = None if timeout is None else time.monotonic() + timeout
        cond = threading.Condition()
        states = self._states(object_ids)
        # Count already-ready objects up front and register callbacks only
        # on pending ones; the callback wakes the waiter ONCE, when the
        # count crosses num_returns — a 1k-ref wait must not pay 1k
        # wakeups (reference: WaitManager's single completion signal).
        pending_states = [st for st in states if not st.ready]
        n_ready = [len(states) - len(pending_states)]

        def on_ready():
            with cond:
                n_ready[0] += 1
                if n_ready[0] >= num_returns:
                    cond.notify()

        if n_ready[0] < num_returns:
            for st in pending_states:
                st.add_callback(on_ready)
            try:
                with cond:
                    while n_ready[0] < num_returns:
                        remaining = None if deadline is None else \
                            deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            break
                        cond.wait(remaining)
            finally:
                # Unregister from still-pending states: polling wait()
                # loops must not accumulate dead closures on never-ready
                # objects.
                for st in pending_states:
                    st.discard_callback(on_ready)
        ready = [o for o, st in zip(object_ids, states) if st.ready]
        ready = ready[:max(num_returns, 0)] if len(ready) > num_returns \
            else ready
        ready_set = set(ready)
        pending = [o for o in object_ids if o not in ready_set]
        return ready, pending

    def _track_view(self, oid: ObjectID, value: Any) -> None:
        """The returned value aliases shared memory: freeing the object
        must wait for the value's death (or never happen if the value
        can't carry a weakref)."""
        if not self._gc_enabled:
            # Without GC there is no deferred-free machinery (no drain
            # thread): frees behave exactly as before view tracking.
            return
        import weakref
        with self._ref_lock:
            if oid in self._view_immortal:
                return
            try:
                weakref.finalize(value, self._on_view_dead, oid)
            except TypeError:
                self._view_immortal.add(oid)
                self._pending_free.discard(oid)
                return
            self._view_counts[oid] = self._view_counts.get(oid, 0) + 1

    def _on_view_dead(self, oid: ObjectID) -> None:
        # weakref.finalize callback: may fire at arbitrary GC points
        # (possibly with _ref_lock held on this thread) — lock-free
        # enqueue only, like ObjectRef.__del__.
        if self._gc_enabled and not self._shutdown:
            self._ref_drop_q.put(("view", oid))

    def _view_dead(self, oid: ObjectID) -> None:
        with self._ref_lock:
            n = self._view_counts.get(oid, 0) - 1
            if n > 0:
                self._view_counts[oid] = n
                return
            self._view_counts.pop(oid, None)
            run_free = oid in self._pending_free
            self._pending_free.discard(oid)
        if run_free:
            self.free([oid])

    def free(self, object_ids: List[ObjectID]) -> None:
        # Objects with live zero-copy views defer their free to view death.
        deferred = []
        with self._ref_lock:
            for oid in object_ids:
                if self._view_counts.get(oid, 0) > 0 or \
                        oid in self._view_immortal:
                    if oid not in self._view_immortal:
                        self._pending_free.add(oid)
                    deferred.append(oid)
        if deferred:
            object_ids = [o for o in object_ids if o not in set(deferred)]
        contained_freed: List[ObjectID] = []
        for oid in object_ids:
            with self._ref_lock:
                self._local_refs.pop(oid, None)
                self._escaped.discard(oid)
                self._dropped.discard(oid)
            # Refs serialized inside this object's value lose their
            # container: release the retention (frees cascade below).
            contained_freed.extend(self._release_contained(oid))
            with self._dir_lock:
                st = self.directory.pop(oid, None)
            if st is not None and st.desc and st.desc[0] == "at":
                # Remote-owned object: route the delete to the owner node.
                proxy = self.nodes.get(NodeID(st.desc[1]))
                if proxy is not None and getattr(proxy, "is_remote", False):
                    from .cluster import FreeObject
                    proxy.send(FreeObject(st.desc[2]))
                # A pulled copy may be cached in the head store too.
                try:
                    self.node.store.delete(oid)
                except Exception as e:
                    telemetry.note_swallowed("runtime.free_object", e)
            shm = self._mapped_segments.pop(oid, None)
            if shm is not None:
                try:
                    shm.close()
                except Exception as e:
                    telemetry.note_swallowed("runtime.free_object", e)
            if st is not None and st.desc and st.desc[0] == "shma":
                if oid in self._arena_pins:
                    self._arena_pins.discard(oid)
                    self.node.store.unpin_key(st.desc[4])
                try:
                    self.node.store.delete(oid)
                except KeyError:
                    pass
                continue
            if st is not None and st.desc and st.desc[0] == "shm":
                try:
                    self.node.store.delete(oid)
                except KeyError:
                    from .object_store import _open_untracked
                    try:
                        seg = _open_untracked(st.desc[1], create=False)
                        seg.close()
                        seg.unlink()
                    except FileNotFoundError:
                        pass
        if contained_freed:
            self.free(contained_freed)

    # ------------------------------------------------------------------ #
    # ownership GC (reference: reference_counter.h local refs + borrows)
    # ------------------------------------------------------------------ #

    def add_local_ref(self, oid: ObjectID) -> None:
        if not self._gc_enabled:
            return
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def enqueue_ref_drop(self, oid: ObjectID) -> None:
        """GC-safe entry point for ObjectRef.__del__ (lock-free put)."""
        if self._gc_enabled and not self._shutdown:
            self._ref_drop_q.put(("drop", oid))

    def _ref_drop_loop(self) -> None:
        import queue as _q
        while True:
            item = self._ref_drop_q.get()
            if item is None or self._shutdown:
                return
            # Batch everything already queued: one _ref_lock acquisition
            # per batch instead of per dropped ref (a 1000-ref get()
            # releases 1000 refs nearly at once).
            batch = [item]
            while len(batch) < 512:
                try:
                    batch.append(self._ref_drop_q.get_nowait())
                except _q.Empty:
                    break
            done = False
            drops: List[ObjectID] = []
            for it in batch:
                if it is None:
                    done = True
                elif it[0] == "drop":
                    drops.append(it[1])
                else:
                    try:
                        self._view_dead(it[1])
                    except Exception as e:
                        telemetry.note_swallowed("runtime.ref_gc", e)
            if drops:
                try:
                    self._apply_ref_drops(drops)
                except Exception as e:
                    telemetry.note_swallowed("runtime.ref_gc", e)
            if done or self._shutdown:
                return

    def _apply_ref_drops(self, oids: List[ObjectID]) -> None:
        """Batched remove_local_ref: same semantics, one lock round."""
        to_free: List[ObjectID] = []
        with self._ref_lock:
            for oid in oids:
                n = self._local_refs.get(oid, 0) - 1
                if n > 0:
                    self._local_refs[oid] = n
                    continue
                self._local_refs.pop(oid, None)
                if not self._collectable_locked(oid):
                    continue
                with self._dir_lock:
                    st = self.directory.get(oid)
                if st is not None and not st.ready:
                    self._dropped.add(oid)
                else:
                    to_free.append(oid)
        if to_free:
            self.free(to_free)

    def remove_local_ref(self, oid: ObjectID) -> None:
        if not self._gc_enabled or self._shutdown:
            return
        free = False
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
            else:
                self._local_refs.pop(oid, None)
                if self._collectable_locked(oid):
                    with self._dir_lock:
                        st = self.directory.get(oid)
                    if st is not None and not st.ready:
                        # Producing task still in flight: collect at
                        # mark_ready instead.
                        self._dropped.add(oid)
                    else:
                        free = True
        if free:
            self.free([oid])

    def mark_escaped(self, oid: ObjectID) -> None:
        """An ObjectRef was pickled into user data: copies may now live
        anywhere (borrowed, reference: reference_counter borrows), so the
        object is never auto-collected."""
        if self._gc_enabled:
            with self._ref_lock:
                self._escaped.add(oid)

    def note_contained(self, outer: ObjectID,
                       inner: List[ObjectID]) -> None:
        """``inner`` refs were serialized inside ``outer``'s value: retain
        them for the outer object's lifetime (released by free(outer)),
        NOT forever (reference: reference_counter.h:44 containment)."""
        if not self._gc_enabled or not inner:
            return
        with self._ref_lock:
            self._contained.setdefault(outer, []).extend(inner)
            for oid in inner:
                self._dep_counts[oid] = self._dep_counts.get(oid, 0) + 1

    def _release_contained(self, outer: ObjectID) -> List[ObjectID]:
        """Drop the outer->inner retention; returns inner objects that
        became collectable (caller frees them outside the lock).  A
        still-pending inner (producer in flight) defers to the _dropped
        set like _apply_ref_drops does — freeing now would let the late
        mark_ready resurrect a zero-reference directory entry and pin
        its payload forever."""
        to_free: List[ObjectID] = []
        with self._ref_lock:
            inner = self._contained.pop(outer, None)
            for oid in inner or ():
                n = self._dep_counts.get(oid, 0) - 1
                if n > 0:
                    self._dep_counts[oid] = n
                    continue
                self._dep_counts.pop(oid, None)
                if not self._collectable_locked(oid):
                    continue
                with self._dir_lock:
                    st = self.directory.get(oid)
                if st is not None and not st.ready:
                    self._dropped.add(oid)
                else:
                    self._dropped.discard(oid)
                    to_free.append(oid)
        return to_free

    def _collectable_locked(self, oid: ObjectID) -> bool:
        return (oid not in self._escaped
                and self._local_refs.get(oid, 0) == 0
                and self._dep_counts.get(oid, 0) == 0)

    def _retain_deps(self, spec: TaskSpec) -> None:
        if not self._gc_enabled:
            return
        deps = [a[1] for a in spec.arg_descs if a[0] == "ref"]
        deps += [d[1] for d in spec.kwarg_descs.values() if d[0] == "ref"]
        # Nested refs (pickled inside arg values) are borrows: retained
        # for the task's lifetime like positional ref args; the worker
        # escalates to escaped via BorrowRetained if it keeps them
        # (reference: reference_counter.h:44).
        deps += list(getattr(spec, "nested_refs", ()) or ())
        if not deps:
            return
        with self._ref_lock:
            if spec.task_id in self._deps_retained:
                return  # already retained (idempotent across resubmits)
            self._deps_retained[spec.task_id] = deps
            for d in deps:
                self._dep_counts[d] = self._dep_counts.get(d, 0) + 1

    def _release_deps(self, task_id: TaskID) -> None:
        if not self._gc_enabled:
            return
        to_free: List[ObjectID] = []
        with self._ref_lock:
            deps = self._deps_retained.pop(task_id, None)
            for d in deps or ():
                n = self._dep_counts.get(d, 0) - 1
                if n > 0:
                    self._dep_counts[d] = n
                else:
                    self._dep_counts.pop(d, None)
                    if self._collectable_locked(d):
                        to_free.append(d)
        if to_free:
            self.free(to_free)

    # ------------------------------------------------------------------ #
    # lineage + reconstruction
    # ------------------------------------------------------------------ #

    def _record_lineage(self, spec: TaskSpec) -> None:
        # Only stateless task outputs are reconstructable by re-execution
        # (actor method results depend on actor state; reference semantics).
        # Streaming tasks are excluded: partial streams can't re-execute
        # idempotently (matches the reference's streaming-generator caveat).
        if spec.actor_id is not None or spec.create_actor_id is not None \
                or not spec.return_ids or getattr(spec, "streaming", False):
            return
        with self._lineage_lock:
            self._lineage[spec.task_id] = spec
            self._lineage.move_to_end(spec.task_id)
            while len(self._lineage) > self._lineage_cap:
                self._lineage.popitem(last=False)

    def _recover_object(self, oid: ObjectID) -> Optional[threading.Event]:
        """Kick lineage re-execution of the task that produced ``oid``.
        Returns an event set when recovery delivers (None if the object is
        not reconstructable)."""
        task_id = oid.task_id()
        with self._lineage_lock:
            spec = self._lineage.get(task_id)
            if spec is None:
                return None
            attempts = self._recover_attempts.get(task_id, 0)
            if attempts >= int(Config.get(
                    "object_reconstruction_max_attempts")):
                return None
            inflight = self._recovering.get(task_id)
            if inflight is not None:
                return inflight
            self._recover_attempts[task_id] = attempts + 1
            done = threading.Event()
            self._recovering[task_id] = done
        # Drop stale driver-side state for the lost returns so the
        # re-produced values land cleanly.  Healthy sibling returns that the
        # driver still holds zero-copy views into (multi-return tasks) are
        # left untouched: deleting their arena slot would corrupt live user
        # arrays, and mark_ready no-ops on their still-set states.
        for rid in spec.return_ids:
            if rid != oid and rid in self._arena_pins:
                continue
            shm = self._mapped_segments.pop(rid, None)
            if shm is not None:
                try:
                    shm.close()
                except Exception as e:
                    telemetry.note_swallowed("runtime.reconstruct_cleanup", e)
            if rid in self._arena_pins:
                self._arena_pins.discard(rid)
                try:
                    self.node.store.unpin_key(rid.binary())
                except Exception as e:
                    telemetry.note_swallowed("runtime.reconstruct_cleanup", e)
            try:
                self.node.store.delete(rid)
            except Exception as e:
                telemetry.note_swallowed("runtime.reconstruct_cleanup", e)
            self._state(rid).reset()
        with self._ref_lock:
            self._escaped.add(oid)  # recovered objects stay pinned
        # Recursively rebuild dependencies that are gone (GC'd after their
        # refs dropped, or lost and never re-produced): a resubmitted task
        # parks in the dependency stage, so unready deps must have their
        # own recovery kicked here or it waits forever.  An unrecoverable
        # dep (no lineage — e.g. a freed ray.put — or attempts exhausted)
        # fails the whole recovery NOW: waiters get ObjectLostError instead
        # of hanging on a task that can never run.
        deps = [a[1] for a in spec.arg_descs if a[0] == "ref"]
        deps += [d[1] for d in spec.kwarg_descs.values() if d[0] == "ref"]
        for dep in deps:
            with self._dir_lock:
                st = self.directory.get(dep)
            if st is None or not st.ready:
                if self._recover_object(dep) is None:
                    err = ("err", serialization.pack_payload(ObjectLostError(
                        f"object {oid} is unrecoverable: its input {dep} "
                        "has no lineage (freed put or evicted spec)",
                        object_id_bytes=oid.binary())))
                    for rid in spec.return_ids:
                        self._state(rid).mark_ready(err)
                    self._finish_recovery(task_id)
                    return None
        self.events.record(task_id.hex(), PENDING_ARGS, name=spec.name,
                           error_message="lineage reconstruction")
        self.submit_spec(spec)
        return done

    def _finish_recovery(self, task_id: TaskID) -> None:
        with self._lineage_lock:
            done = self._recovering.pop(task_id, None)
        if done is not None:
            done.set()

    def _lost_object_in_error(self, error_desc) -> Optional[ObjectID]:
        """If a task failed because an input object was lost, name it."""
        if not error_desc or error_desc[0] != "err":
            return None
        try:
            exc = serialization.unpack_payload(error_desc[1])
        except Exception:
            return None
        inner = getattr(exc, "cause", exc)
        oid_bytes = getattr(inner, "object_id_bytes", None)
        if isinstance(inner, ObjectLostError) and oid_bytes:
            try:
                return ObjectID(oid_bytes)
            except ValueError:
                return None
        return None

    # ------------------------------------------------------------------ #
    # task submission
    # ------------------------------------------------------------------ #

    def submit_spec(self, spec: TaskSpec) -> None:
        if spec.fn_id is not None and spec.fn_blob is not None and \
                spec.fn_id not in self._fn_table:
            # Function table (reference: GCS function_manager): workers
            # fetch by id when a stripped spec misses their local cache.
            self._fn_table[spec.fn_id] = spec.fn_blob
        if self._gc_enabled:
            # Pre-create return states so a ref dropped while the task is
            # in flight is distinguishable from a never-existed object:
            # remove_local_ref defers those frees to mark_ready via
            # _dropped, which needs the pending state to exist.
            for oid in spec.return_ids:
                self._state(oid)
        self._retain_deps(spec)
        self._record_lineage(spec)
        if spec.actor_id is not None:
            self.events.record(
                spec.task_id.hex(), PENDING_ARGS, name=spec.name,
                task_type="ACTOR_TASK", actor_id=spec.actor_id.hex())
            self._submit_actor_task(spec)
        elif spec.create_actor_id is not None:
            self.events.record(
                spec.task_id.hex(), PENDING_ARGS, name=spec.name,
                task_type="ACTOR_CREATION_TASK",
                actor_id=spec.create_actor_id.hex())
            self._submit_actor_creation(spec)
        else:
            self.events.record(spec.task_id.hex(), PENDING_ARGS,
                               name=spec.name)
            self.scheduler.submit(spec, self._dispatch_normal)

    def _resolve(self, spec: TaskSpec):
        """Resolve ref args to descriptors; raises _DepsPending if any dep
        went back to pending (lost + reconstruction in flight) between the
        scheduler's readiness check and now."""
        pending: List[ObjectID] = []

        def desc_of(oid):
            st = self._state(oid)
            d = st.desc
            if d is None:
                pending.append(oid)
            return d

        args = []
        for kind, payload in spec.arg_descs:
            if kind == "ref":
                args.append(desc_of(payload))
            else:
                args.append(("inline", payload))
        kwargs = {}
        for k, (kind, payload) in spec.kwarg_descs.items():
            if kind == "ref":
                kwargs[k] = desc_of(payload)
            else:
                kwargs[k] = ("inline", payload)
        if pending:
            raise _DepsPending(pending)
        return args, kwargs

    def _after_deps(self, oids: List[ObjectID], fn: Callable[[], None]) -> None:
        """Run fn once every oid is (re-)ready."""
        remaining = {"n": len(oids)}
        lock = threading.Lock()

        def one_ready():
            with lock:
                remaining["n"] -= 1
                done = remaining["n"] == 0
            if done:
                fn()

        for oid in oids:
            self._state(oid).add_callback(one_ready)

    def _xfer_loop(self) -> None:
        while True:
            fn = self._xfer_q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                import traceback
                traceback.print_exc()

    def _offload(self, fn, ordered: bool = False) -> None:
        """Run `fn` off the caller's thread in cluster mode (it may block on
        cross-node object pulls), inline otherwise.  ``ordered`` work shares
        one queue thread (per-actor dispatch ordering); the rest runs on a
        small pool."""
        if self._xfer_q is None:
            fn()
        elif ordered:
            self._xfer_q.put(fn)
        else:
            self._xfer_pool.submit(self._safely, fn)

    @staticmethod
    def _safely(fn) -> None:
        try:
            fn()
        except Exception:
            import traceback
            traceback.print_exc()

    def _requeue_or_fail(self, spec: TaskSpec, reason: str) -> None:
        if spec.actor_id is None and spec.create_actor_id is None and \
                spec.retry_count < spec.max_retries:
            spec.retry_count += 1
            self.submit_spec(spec)
        elif spec.create_actor_id is not None:
            # Creation never completed; re-place it (no restart consumed).
            self._submit_actor_creation(spec)
        elif spec.actor_id is not None:
            self._fail_task(spec, ActorError(spec.actor_id, reason))
        else:
            self._fail_task(spec, WorkerCrashedError(reason))

    def _pipeline_topup(self, budget: int = 2) -> None:
        """Move up to ``budget`` queued tasks into worker pipeline slots
        (bounded so one TaskDone never monopolizes the poller thread)."""
        for _ in range(budget):
            nxt = self.scheduler.take_pipelineable()
            if nxt is None:
                return
            if not self._try_pipeline(nxt.spec):
                # No pipeline room: route back through normal (booked)
                # submission.
                self.scheduler.submit(nxt.spec, nxt.dispatch)
                return

    def _pipeline_cap(self, node_id: NodeID) -> int:
        """In-flight pipelined-task cap for a remote node: ~2 queued-ahead
        tasks per pooled worker (reference: the per-worker in-flight cap of
        the C++ submitter's pipelining)."""
        info = self.controller.nodes.get(node_id)
        cpus = info.total_resources.get("CPU") if info is not None else 1.0
        return max(2, min(32, int(2 * (cpus or 1.0))))

    def _try_pipeline(self, spec: TaskSpec) -> bool:
        """Scheduler callback when the cluster is full: queue the task
        ahead on a busy worker (no booking) to hide the done->dispatch
        round trip.  Local workers take it synchronously; remote nodes
        take it under per-node credit accounting, answering
        UpPipelineReject when their pools have no queue room."""
        if len(self.nodes) == 1 and self._puller is None \
                and not self.node.has_pipeline_room():
            # Cheap precheck: a full pool means resolve/queue/requeue
            # below is guaranteed wasted work (the topup loop runs on
            # every TaskDone).
            return False
        try:
            args, kwargs = self._resolve(spec)
        except _DepsPending:
            return False
        if len(self.nodes) == 1 and self._puller is None:
            with self._running_lock:
                self._running[spec.task_id] = _RunningTask(spec,
                                                           self.node_id)
            self._pipelined.add(spec.task_id)
            if self.node.dispatch_pipelined(spec, args, kwargs):
                self.events.record(spec.task_id.hex(), SUBMITTED_TO_NODE,
                                   node_id=self.node_id.hex())
                return True
            self._pipelined.discard(spec.task_id)
            with self._running_lock:
                self._running.pop(spec.task_id, None)
            return False
        # Cluster: pick the remote node with the most spare credit (the
        # local node is excluded — its dispatches ride the ordered
        # transfer queue, where queue-ahead wins nothing).  Credit
        # mutations happen under _pipeline_lock: submit threads and the
        # completion (poller) thread race here, and a lost decrement
        # would leak credits until pipelining silently turned off.
        now = time.monotonic()
        with self._pipeline_lock:
            best, best_spare = None, 0
            for nid, node in self.nodes.items():
                if not getattr(node, "is_remote", False):
                    continue
                if self._pipeline_cooldown.get(nid, 0.0) > now:
                    continue
                spare = self._pipeline_cap(nid) - \
                    self._pipeline_credits.get(nid, 0)
                if spare > best_spare:
                    best, best_spare = nid, spare
            if best is None:
                return False
            node = self.nodes.get(best)
            if node is None:
                return False
            self._pipelined_node[spec.task_id] = best
            self._pipeline_credits[best] = \
                self._pipeline_credits.get(best, 0) + 1
        with self._running_lock:
            self._running[spec.task_id] = _RunningTask(spec, best)
        self._pipelined.add(spec.task_id)
        node.dispatch_task(spec, args, kwargs, pipelined=True)
        self.events.record(spec.task_id.hex(), SUBMITTED_TO_NODE,
                           node_id=best.hex())
        return True

    def _return_pipeline_credit(self, task_id: TaskID) -> None:
        with self._pipeline_lock:
            nid = self._pipelined_node.pop(task_id, None)
            if nid is not None and nid in self._pipeline_credits:
                self._pipeline_credits[nid] = max(
                    0, self._pipeline_credits[nid] - 1)

    def on_pipeline_reject(self, spec: TaskSpec, node_id: NodeID) -> None:
        """A remote node had no pipeline room: return the credit, put the
        node on a short pipelining cooldown (otherwise the empty-queue
        fast path would bounce the task straight back, re-localizing its
        args each round trip), and run the task through normal (booked)
        scheduling."""
        with self._running_lock:
            self._running.pop(spec.task_id, None)
        self._pipelined.discard(spec.task_id)
        self._return_pipeline_credit(spec.task_id)
        with self._pipeline_lock:
            self._pipeline_cooldown[node_id] = time.monotonic() + 0.5
        self.scheduler.submit(spec, self._dispatch_normal)

    def _dispatch_normal(self, spec: TaskSpec, node_id: NodeID) -> None:
        try:
            args, kwargs = self._resolve(spec)
        except _DepsPending:
            # A dep went back to pending (reconstruction): give back the
            # booked resources and let the dependency stage re-hold it.
            if not spec.resources.is_empty() or spec.placement_group is not None:
                self.scheduler.release(node_id, spec.resources,
                                       spec.placement_group,
                                       spec.bundle_index)
            self.scheduler.submit(spec, self._dispatch_normal)
            return
        node = self.nodes.get(node_id)
        if node is None:
            # Node died between placement and dispatch.
            self._requeue_or_fail(spec, f"node {node_id} died before "
                                  f"dispatch of {spec.name}")
            return
        if not getattr(node, "is_remote", False) and self._puller is not None \
                and _has_remote_desc(args, kwargs):
            # Local dispatch with remote args: pull them home on the
            # transfer thread — pulls must not block the scheduler loop.
            self._track(spec, node_id)

            def run():
                a, k = self._puller.localize_all(args, kwargs)
                node.dispatch_task(spec, a, k)
            self._offload(run)
            return
        self._track(spec, node_id)
        node.dispatch_task(spec, args, kwargs)

    # -- actors ---------------------------------------------------------- #

    def register_actor(self, info: ActorInfo) -> None:
        self.controller.register_actor(info)
        with self._actors_lock:
            self._actors[info.actor_id] = _ActorRuntimeState()

    def _submit_actor_creation(self, spec: TaskSpec) -> None:
        self.controller.set_actor_state(spec.create_actor_id, PENDING_CREATION)
        self.scheduler.submit(spec, self._dispatch_normal)

    def _actor_state(self, actor_id: ActorID) -> _ActorRuntimeState:
        # Lock-free read first: dict.get is GIL-atomic and entries are
        # never replaced once inserted, so the hot path (one lookup per
        # direct call) skips the lock.
        st = self._actors.get(actor_id)  # ray-tpu: noqa[RT401]
        if st is not None:
            return st
        with self._actors_lock:
            st = self._actors.get(actor_id)
            if st is None:
                st = _ActorRuntimeState()
                self._actors[actor_id] = st
            return st

    def _submit_actor_task(self, spec: TaskSpec) -> None:
        ast = self._actor_state(spec.actor_id)
        info = self.controller.get_actor(spec.actor_id)
        if info is not None and info.state == DEAD:
            self._fail_task(spec, ActorError(spec.actor_id, info.death_cause))
            return
        with ast.lock:
            seq = ast.next_seq
            ast.next_seq += 1
        deps = [a[1] for a in spec.arg_descs if a[0] == "ref"]
        deps += [d[1] for d in spec.kwarg_descs.values() if d[0] == "ref"]
        if not deps:
            # Fast path: no ref args — resolution is a pure re-tag of the
            # inline payloads, nothing can go back to pending.
            self._enqueue_actor_dispatch(
                ast, spec, seq,
                [("inline", p) for _k, p in spec.arg_descs],
                {k: ("inline", p) for k, (_kind, p)
                 in spec.kwarg_descs.items()})
            return
        unresolved = [d for d in deps if not self._object_ready(d)]

        def on_deps_ready():
            try:
                args, kwargs = self._resolve(spec)
            except _DepsPending as dp:
                # Dep reset under us (lost + reconstructing): wait again.
                self._after_deps(dp.oids, on_deps_ready)
                return
            self._enqueue_actor_dispatch(ast, spec, seq, args, kwargs)

        if not unresolved:
            on_deps_ready()
        else:
            self._after_deps(list(unresolved), on_deps_ready)

    def _enqueue_actor_dispatch(self, ast: _ActorRuntimeState, spec: TaskSpec,
                                seq: int, args, kwargs) -> None:
        """Strict per-actor ordering: dispatch seq k only after k-1
        (reference: sequential_actor_submit_queue.h)."""
        to_send = []
        with ast.lock:
            ast.ready_buffer[seq] = (spec, args, kwargs)
            while ast.next_dispatch in ast.ready_buffer:
                item = ast.ready_buffer.pop(ast.next_dispatch)
                ast.next_dispatch += 1
                to_send.append(item)
        for item in to_send:
            self._dispatch_to_actor_worker(ast, *item)

    def _dispatch_to_actor_worker(self, ast: _ActorRuntimeState,
                                  spec: TaskSpec, args, kwargs) -> None:
        with ast.lock:
            if ast.worker_id is None:
                ast.pending_bind.append((spec, args, kwargs))
                return
            node_id, worker_id = ast.node_id, ast.worker_id
            # Classic dispatches in flight block the driver channel from
            # activating (frames on two transports must never reorder).
            ast.classic_inflight.add(spec.task_id)
        node = self.nodes.get(node_id)
        if node is None:
            self._fail_task(spec, ActorError(
                spec.actor_id, "actor's node left the cluster"))
            return
        if not getattr(node, "is_remote", False) and self._xfer_q is not None:
            # All local actor dispatches ride the transfer queue in cluster
            # mode: localization may block, and a faster no-pull task must
            # not overtake an earlier pulling one (per-actor ordering).
            self._track(spec, node_id)

            def run():
                a, k = self._puller.localize_all(args, kwargs)
                node.dispatch_task(spec, a, k, target_worker=worker_id)
            self._offload(run, ordered=True)
            return
        if getattr(node, "is_remote", False):
            self._track(spec, node_id)
            node.dispatch_task(spec, args, kwargs, target_worker=worker_id)
        else:
            # Local fast path: insert into running without the
            # SUBMITTED_TO_WORKER event — dispatch_actor_task records
            # RUNNING immediately after anyway.
            with self._running_lock:
                self._running[spec.task_id] = _RunningTask(spec, node_id)
            node.dispatch_actor_task(spec, args, kwargs, worker_id)

    def submit_actor_direct(self, actor_id: ActorID, task_id: TaskID,
                            name: str, method_name: str,
                            return_ids: List[ObjectID], args: list,
                            kwargs: dict, max_concurrency: int) -> bool:
        """Fast-path actor method call (reference: the direct caller->actor
        submission stream, actor_task_submitter.h:68 — the driver pushes
        the call straight onto the actor worker's connection).

        Skips TaskSpec construction, task events, the running table and
        on_task_done: the call frame goes directly to the bound worker and
        the reply is routed by ``on_direct_task_done`` via
        ``_direct_inflight``.  Falls back (returns False) whenever ordering
        needs the full path: worker unbound/restarting, or queued calls
        ahead (per-caller submission order must hold).

        Cluster mode: the driver opens its own caller->actor channel
        (direct.py DirectChannel over TCP) to actors on remote nodes — and
        to local actors whose classic dispatches ride the ordered transfer
        queue — activating it (sticky) only at quiescence: no queued or
        in-flight classic dispatches, so a channel frame can never
        overtake a classic one.  Channel calls record no task events
        (mirrors worker->worker direct calls); calls with ref args still
        take the classic path, which is unordered relative to the channel
        — the same documented trade the worker-side channels make."""
        ast = self._actor_state(actor_id)
        tb = task_id.binary()
        if ast.driver_mode == "direct":
            return self._submit_via_channel(
                ast, actor_id, tb, name, method_name, return_ids, args,
                kwargs, max_concurrency)
        with ast.lock:
            if (ast.worker_id is None or ast.pending_bind
                    or ast.next_dispatch != ast.next_seq):
                return False
            node = self.nodes.get(ast.node_id)
            if node is None:
                return False
            if getattr(node, "is_remote", False) or \
                    self._xfer_q is not None:
                if ast.classic_inflight or ast.direct_addr is None:
                    return False  # not quiescent yet: classic this call
                ast.driver_mode = "direct"
            if ast.driver_mode == "direct":
                pass  # channel submission happens outside ast.lock
            else:
                return self._submit_direct_local(
                    ast, node, actor_id, tb, name, method_name,
                    return_ids, args, kwargs, max_concurrency)
        return self._submit_via_channel(
            ast, actor_id, tb, name, method_name, return_ids, args,
            kwargs, max_concurrency)

    def _submit_direct_local(self, ast, node, actor_id: ActorID,
                             tb: bytes, name: str, method_name: str,
                             return_ids: List[ObjectID], args: list,
                             kwargs: dict, max_concurrency: int) -> bool:
        """The in-process fast path (caller holds ast.lock)."""
        # Claim the sequence slot and ship while still holding
        # ast.lock so a concurrently submitted call claiming seq N+1
        # cannot reach the worker pipe before this frame (seq N).
        ast.next_seq += 1
        ast.next_dispatch += 1
        if self._gc_enabled:
            # Pending states must exist before a ref drop can arrive
            # (see submit_spec's pre-create note).  The oids are freshly
            # minted — no concurrent creator exists — so GIL-atomic
            # setitem is enough (skips the directory lock).
            directory = self.directory  # ray-tpu: noqa[RT401]
            for oid in return_ids:
                if oid not in directory:
                    directory[oid] = ObjectState()
        with self._direct_lock:
            self._direct_inflight[tb] = (actor_id, return_ids, name)
        frame = (_wire.RUN_TASK, tb, name, None, None, method_name,
                 tuple(r.binary() for r in return_ids),
                 actor_id.binary(), False, max_concurrency, None,
                 args, kwargs, None)
        if not node.send_direct(ast.worker_id, frame):
            with self._direct_lock:
                self._direct_inflight.pop(tb, None)
            desc = ("err", serialization.pack_payload(ActorError(
                actor_id, "actor worker died before the call was sent")))
            for oid in return_ids:
                self.mark_ready(oid, desc)
        return True

    def _submit_via_channel(self, ast, actor_id: ActorID, tb: bytes,
                            name: str, method_name: str,
                            return_ids: List[ObjectID], args: list,
                            kwargs: dict, max_concurrency: int) -> bool:
        """Driver->actor direct channel (cluster mode): the frame rides
        the driver's own TCP connection to the actor's worker — the
        head's control plane sees neither the call nor its inline reply
        (reference: caller->executor pushes as the cluster default,
        normal_task_submitter.cc:516, actor_task_submitter.h:68)."""
        ch = ast.driver_ch
        if ch is None:
            with ast.lock:
                ch = ast.driver_ch
                if ch is None:
                    from .direct import DirectChannel
                    ch = DirectChannel(_DriverChannelOwner(self), actor_id)
                    ast.driver_ch = ch
                    with ch.lock:
                        ch._ensure_resolver_locked()
        # Object states must exist before the frame ships: the inline
        # reply can land on the channel's recv thread immediately.
        self._states(return_ids)
        frame = (_wire.RUN_TASK, tb, name, None, None, method_name,
                 tuple(r.binary() for r in return_ids),
                 actor_id.binary(), False, max_concurrency, None,
                 args, kwargs, None)
        ch.submit(frame, return_ids)
        return True

    def on_direct_task_done(self, t: tuple) -> bool:
        """Route a wire TaskDone for a direct call (pre-decode): mark the
        caller-held return refs ready.  Returns False for non-direct tasks
        so the node runs the full TaskDone path."""
        with self._direct_lock:
            entry = self._direct_inflight.pop(t[1], None)
        if entry is None:
            return False
        aid, return_ids, name = entry
        error = t[4]
        # One terminal event per direct call keeps the state API's task
        # view complete; the intermediate states are intentionally skipped
        # on this path.
        if error is not None:
            err_repr = None
            try:
                err_repr = repr(serialization.unpack_payload(error[1]))
            except Exception as e:
                telemetry.note_swallowed("runtime.error_repr", e)
            self.events.record(TaskID(t[1]).hex(), FAILED, name=name,
                               task_type="ACTOR_TASK", actor_id=aid.hex(),
                               error_message=err_repr)
            for oid in return_ids:
                self.mark_ready(oid, error)
            return True
        self.events.record(TaskID(t[1]).hex(), FINISHED, name=name,
                           task_type="ACTOR_TASK", actor_id=aid.hex())
        for ob, desc in t[3]:
            self.mark_ready(ObjectID(ob), desc)
        return True

    def _fail_direct_inflight(self, actor_id: ActorID, reason: str) -> None:
        with self._direct_lock:
            failed = [(tb, rids) for tb, (aid, rids, _name)
                      in self._direct_inflight.items() if aid == actor_id]
            for tb, _ in failed:
                self._direct_inflight.pop(tb, None)
        if not failed:
            return
        desc = ("err", serialization.pack_payload(
            ActorError(actor_id, reason)))
        for _tb, rids in failed:
            for oid in rids:
                self.mark_ready(oid, desc)

    def bind_actor_worker(self, actor_id: ActorID, node_id: NodeID,
                          worker_id: WorkerID) -> None:
        ast = self._actor_state(actor_id)
        with ast.lock:
            ast.worker_id = worker_id
            ast.node_id = node_id
            pending, ast.pending_bind = ast.pending_bind, []
        for item in pending:
            self._dispatch_to_actor_worker(ast, *item)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        ast = self._actor_state(actor_id)
        info = self.controller.get_actor(actor_id)
        if info is not None and no_restart:
            info.max_restarts = info.num_restarts  # no further restarts
        if ast.worker_id is not None and ast.node_id is not None:
            self.nodes[ast.node_id].kill_actor_worker(ast.worker_id)

    # ------------------------------------------------------------------ #
    # events from the node plane
    # ------------------------------------------------------------------ #

    def note_task_running(self, task_id: TaskID, node_id: NodeID,
                          worker_id: WorkerID) -> None:
        with self._running_lock:
            rt = self._running.get(task_id)
            if rt is not None:
                rt.worker_id = worker_id
        self.events.record(task_id.hex(), RUNNING, node_id=node_id.hex(),
                           worker_id=worker_id.hex())

    def _track(self, spec: TaskSpec, node_id: NodeID) -> None:
        with self._running_lock:
            self._running[spec.task_id] = _RunningTask(spec, node_id)
        self.events.record(spec.task_id.hex(), SUBMITTED_TO_NODE,
                           node_id=node_id.hex())

    def on_task_done(self, msg: TaskDone, node_id: NodeID) -> None:
        with self._running_lock:
            running = self._running.pop(msg.task_id, None)
        spec = running.spec if running else None
        if spec is not None and spec.actor_id is not None:
            with self._actors_lock:
                ast = self._actors.get(spec.actor_id)
            if ast is not None:
                ast.classic_inflight.discard(spec.task_id)
        resubmit = False
        if msg.error is not None:
            # A task that failed because an *input* object was lost gets
            # resubmitted once the input's lineage re-execution is kicked
            # off — the scheduler's dependency stage holds it until the
            # rebuilt value lands (reference: task resubmission on
            # ObjectLostError, object_recovery_manager.h).
            lost = self._lost_object_in_error(msg.error)
            if lost is not None and spec is not None \
                    and spec.actor_id is None \
                    and spec.create_actor_id is None \
                    and self._recover_object(lost) is not None:
                resubmit = True
                self.events.record(
                    msg.task_id.hex(), PENDING_ARGS, name=spec.name,
                    error_message="input lost; awaiting reconstruction")
            else:
                err = None
                try:
                    err = repr(serialization.unpack_payload(msg.error[1]))
                except Exception as e:
                    telemetry.note_swallowed("runtime.error_repr", e)
                self.events.record(msg.task_id.hex(), FAILED,
                                   error_message=err)
                self._export_event("EXPORT_TASK", {
                    "task_id": msg.task_id.hex(), "state": FAILED,
                    "name": spec.name if spec else None,
                    "error_message": err})
                for oid in (spec.return_ids if spec
                            else [r[0] for r in msg.results]):
                    self.mark_ready(oid, msg.error)
                if spec is not None and getattr(spec, "streaming", False):
                    self._fail_stream(msg.task_id, msg.error)
                self._finish_recovery(msg.task_id)
        else:
            self.events.record(msg.task_id.hex(), FINISHED)
            for oid, desc in msg.results:
                self.mark_ready(oid, desc)
            # Safe bare read: empty-dict fast path; a stale non-empty
            # view just takes the locked _finish_recovery slow path.
            if self._recovering:  # ray-tpu: noqa[RT401]
                self._finish_recovery(msg.task_id)
        if spec is not None and spec.task_id in self._pipelined:
            # Pipelined task: never booked resources — nothing to release
            # or exchange, but the freed worker-queue slot can take the
            # next queued task.
            self._pipelined.discard(spec.task_id)
            self._return_pipeline_credit(spec.task_id)
            self._pipeline_topup()
        elif spec is not None and spec.create_actor_id is None:
            # Actor creation keeps its resources for the actor's lifetime.
            if not spec.resources.is_empty() or spec.placement_group is not None:
                from .resources import TPU as _TPU
                if msg.error is None and spec.actor_id is None \
                        and spec.placement_group is None \
                        and spec.runtime_env is None \
                        and spec.scheduling_strategy is None \
                        and spec.resources.get(_TPU) == 0:
                    # Lease reuse: hand the booking straight to the next
                    # queued task of this class and dispatch it onto the
                    # just-freed worker — no release/re-book round trip
                    # through the scheduler loop.
                    nxt = self.scheduler.exchange_finished(node_id, spec)
                    if nxt is not None:
                        self.scheduler._dispatch_safely(
                            nxt.spec, nxt.dispatch, node_id)
                        # Keep worker queues non-empty: a backlogged class
                        # also tops up the pipeline window so workers never
                        # idle through the done->dispatch round trip.
                        self._pipeline_topup()
                else:
                    self.scheduler.release(node_id, spec.resources,
                                           spec.placement_group,
                                           spec.bundle_index)
        if resubmit:
            # Deps stay retained across the resubmit (releasing first could
            # let GC free a sibling input that nothing would re-produce).
            self.submit_spec(spec)
        # Safe bare read: empty-dict fast path; _release_deps re-checks
        # membership under its own lock.
        elif self._deps_retained:  # ray-tpu: noqa[RT401]
            self._release_deps(msg.task_id)

    def on_dispatch_failed(self, spec: TaskSpec, reason: str,
                           lost_object_bytes: Optional[bytes] = None) -> None:
        with self._running_lock:
            self._running.pop(spec.task_id, None)
        if lost_object_bytes is not None and spec.actor_id is None \
                and spec.create_actor_id is None:
            # A dependency vanished between resolve and dispatch: rebuild it
            # via lineage and resubmit (the dependency stage holds the task
            # until the rebuilt value lands).
            try:
                lost = ObjectID(lost_object_bytes)
            except ValueError:
                lost = None
            if lost is not None and self._recover_object(lost) is not None:
                # Deps stay retained across the resubmit (see on_task_done).
                self.submit_spec(spec)
                return
        self._fail_task(spec, WorkerCrashedError(reason))

    def fail_task_bytes(self, task_id_bytes: bytes, return_id_bytes,
                        reason: str) -> None:
        """Fail a task known only by its wire-frame ids (sender-side
        serialization failure).  The tracked running spec — if still there
        — provides the resource booking to release; without it, fall back
        to erroring the raw return ids."""
        try:
            task_id = TaskID(task_id_bytes)
        except ValueError:
            return
        # A direct call whose frame never serialized: clear its in-flight
        # entry so long-lived actors don't accumulate dead records.
        with self._direct_lock:
            self._direct_inflight.pop(task_id_bytes, None)
        with self._running_lock:
            running = self._running.pop(task_id, None)
        if running is not None:
            spec = running.spec
            if spec.task_id in self._pipelined:
                self._pipelined.discard(spec.task_id)
                self._return_pipeline_credit(spec.task_id)
            elif spec.create_actor_id is None and (
                    not spec.resources.is_empty()
                    or spec.placement_group is not None):
                self.scheduler.release(running.node_id, spec.resources,
                                       spec.placement_group,
                                       spec.bundle_index)
            self._fail_task(spec, WorkerCrashedError(reason))
            return
        self.events.record(task_id.hex(), FAILED, error_message=reason)
        desc = ("err", serialization.pack_payload(WorkerCrashedError(reason)))
        for rb in return_id_bytes:
            try:
                self.mark_ready(ObjectID(rb), desc)
            except ValueError:
                pass
        self._release_deps(task_id)
        self._finish_recovery(task_id)

    def _fail_task(self, spec: TaskSpec, exc: Exception) -> None:
        if spec.actor_id is not None:
            with self._actors_lock:
                ast = self._actors.get(spec.actor_id)
            if ast is not None:
                ast.classic_inflight.discard(spec.task_id)
        self.events.record(spec.task_id.hex(), FAILED, name=spec.name,
                           error_message=repr(exc))
        self._export_event("EXPORT_TASK", {
            "task_id": spec.task_id.hex(), "state": FAILED,
            "name": spec.name, "error_message": repr(exc)})
        self._release_deps(spec.task_id)
        desc = ("err", serialization.pack_payload(exc))
        for oid in spec.return_ids:
            self.mark_ready(oid, desc)
        if getattr(spec, "streaming", False):
            self._fail_stream(spec.task_id, desc)
        self._finish_recovery(spec.task_id)

    def _fail_stream(self, task_id: TaskID, err_desc) -> None:
        """Publish an error at the first unpublished stream index so a
        blocked ObjectRefGenerator raises instead of hanging forever."""
        i = 0
        while True:
            st = self._state(ObjectID.of(task_id, i))
            if not st.ready:
                st.mark_ready(err_desc)
                self.scheduler.notify_object_ready(ObjectID.of(task_id, i))
                return
            i += 1
            if i > 1 << 20:
                return

    def on_worker_died(self, worker_id: WorkerID, node_id: NodeID,
                       running_tasks: List[TaskID],
                       actor_id: Optional[ActorID],
                       reason: str = "") -> None:
        if self._shutdown:
            return
        specs: List[TaskSpec] = []
        with self._running_lock:
            for tid in running_tasks:
                rt = self._running.pop(tid, None)
                if rt is not None:
                    specs.append(rt.spec)
        oom = reason.startswith("OOM-killed")
        # Direct actor calls bypass the running table (submit_actor_direct):
        # count them so a busy actor's death still registers as unexpected.
        n_direct = 0
        if actor_id is not None:
            with self._direct_lock:
                n_direct = sum(1 for (aid, _r, _n)
                               in self._direct_inflight.values()
                               if aid == actor_id)
        self._export_event("EXPORT_WORKER", {
            "worker_id": worker_id.hex(), "node_id": node_id.hex(),
            "state": "DEAD", "reason": reason or None,
            "actor_id": actor_id.hex() if actor_id is not None else None,
            "num_running_tasks": len(specs) + n_direct})
        if specs or n_direct:
            # Dying WHILE running tasks is the unexpected case worth
            # forensics (clean pool reaping and idle actor kills are not).
            # A death on a draining node is the EXPECTED half of a
            # preemption: tag the bundle so the postmortem reads
            # "preempted", not "mystery crash".
            node = self.controller.nodes.get(node_id)
            draining = bool(node is not None and node.draining)
            self._maybe_death_bundle(
                f"worker_death_{'preempted_' if draining else ''}"
                f"{worker_id.hex()[:8]}",
                {"worker_id": worker_id.hex(),
                 "reason": "preempted" if draining else reason,
                 "worker_reason": reason,
                 "node_draining": draining,
                 "running_tasks": [t.hex() for t in running_tasks],
                 "direct_calls_inflight": n_direct})
        for spec in specs:
            if spec.task_id in self._pipelined:
                # Pipelined task: no booking to release; the resubmit
                # below goes through normal (booked) submission.
                self._pipelined.discard(spec.task_id)
                self._return_pipeline_credit(spec.task_id)
            elif spec.create_actor_id is None and (
                    not spec.resources.is_empty()
                    or spec.placement_group is not None):
                self.scheduler.release(node_id, spec.resources,
                                       spec.placement_group, spec.bundle_index)
            if spec.actor_id is None and spec.create_actor_id is None and \
                    spec.retry_count < spec.max_retries:
                spec.retry_count += 1
                self.submit_spec(spec)
            elif spec.actor_id is not None:
                self._fail_task(spec, ActorError(
                    spec.actor_id,
                    f"worker died while running {spec.name}"
                    + (f" ({reason})" if reason else "")))
            elif spec.create_actor_id is None:
                err_cls = OutOfMemoryError if oom else WorkerCrashedError
                self._fail_task(spec, err_cls(
                    f"worker {worker_id} died while running {spec.name}"
                    + (f" ({reason})" if reason else "")))
        if actor_id is not None:
            self._fail_direct_inflight(
                actor_id, "worker died while running a direct actor call"
                + (f" ({reason})" if reason else ""))
            self._on_actor_worker_death(actor_id, node_id)

    def _on_actor_worker_death(self, actor_id: ActorID, node_id: NodeID) -> None:
        info = self.controller.get_actor(actor_id)
        if info is None or info.state == DEAD:
            return
        ast = self._actor_state(actor_id)
        with ast.lock:
            ast.worker_id = None
            ast.node_id = None
            ast.direct_addr = None
            # Classic frames to the dead worker can't be in flight anymore;
            # a stale entry would wedge driver-channel activation forever.
            ast.classic_inflight.clear()
        # Release the actor's held creation resources.
        if info.creation_spec is not None:
            cs = info.creation_spec
            if not cs.resources.is_empty() or cs.placement_group is not None:
                self.scheduler.release(node_id, cs.resources,
                                       cs.placement_group, cs.bundle_index)
        if info.num_restarts < info.max_restarts:
            info.num_restarts += 1
            self.controller.set_actor_state(actor_id, RESTARTING)
            self._submit_actor_creation(
                self._restart_creation_spec(actor_id, info.creation_spec))
        else:
            self.controller.set_actor_state(actor_id, DEAD,
                                            death_cause="worker died")
            with ast.lock:
                pending = ast.pending_bind + list(ast.ready_buffer.values())
                ast.pending_bind = []
                ast.ready_buffer.clear()
            for spec, _a, _k in pending:
                self._fail_task(spec, ActorError(actor_id, "actor died"))

    def on_node_died(self, node_id: NodeID) -> None:
        """A joined node's control connection dropped: fail/retry its tasks,
        restart its actors elsewhere, re-plan its PG bundles (reference:
        gcs_node_manager.cc node death fan-out + gcs_actor_manager restart;
        gcs_placement_group_manager bundle rescheduling)."""
        if self._shutdown:
            return
        self.nodes.pop(node_id, None)
        with self._node_views_lock:
            self._node_views.pop(node_id, None)
        self.controller.mark_node_dead(node_id, "connection lost")
        # Death fan-out reruns/fails its work: a later same-identity
        # re-attach (even across a head restart) must be refused.
        self.controller.drop_revivable(node_id.binary())
        self.scheduler.remove_node(node_id)
        telemetry.set_gauge("ray_tpu_node_draining",
                            len(self.controller.draining_nodes()))

        specs: List[TaskSpec] = []
        with self._running_lock:
            for tid, rt in list(self._running.items()):
                if rt.node_id == node_id:
                    self._running.pop(tid, None)
                    specs.append(rt.spec)
        with self._pipeline_lock:
            self._pipeline_credits.pop(node_id, None)
            self._pipeline_cooldown.pop(node_id, None)
        for spec in specs:
            # Pipelined entries must clear BEFORE the resubmit: the retried
            # task reuses its task_id, and a stale _pipelined entry would
            # make its eventual TaskDone skip the booked-resource release.
            if spec.task_id in self._pipelined:
                self._pipelined.discard(spec.task_id)
                with self._pipeline_lock:
                    self._pipelined_node.pop(spec.task_id, None)
            # Creation tasks are re-placed (the actor never came up, so no
            # restart is consumed); retryable tasks resubmit; others fail.
            self._requeue_or_fail(
                spec, f"node {node_id} died while running {spec.name}")

        # Actors that lived there: restart elsewhere via the FSM.
        with self._actors_lock:
            lost = [aid for aid, ast in self._actors.items()
                    if ast.node_id == node_id]
        for aid in lost:
            self._on_actor_worker_death(aid, node_id)

        # PG bundles committed to the dead node: re-plan just those bundles
        # on the surviving nodes.
        for pg in list(self.controller.placement_groups.values()):
            if any(b.node_id == node_id for b in pg.bundles):
                self.scheduler.reschedule_lost_bundles(pg, node_id)

    def ctl_node_data_address(self, node_id_bytes: bytes):
        """Data-plane address lookup for peer pulls (the location oracle)."""
        if self.head_server is None:
            return None
        return self.head_server.node_data_address(node_id_bytes)

    def on_actor_state(self, msg: ActorStateMsg, node_id: NodeID,
                       worker_id: WorkerID) -> None:
        if msg.state == "alive":
            addr = getattr(msg, "direct_addr", None)
            if addr is not None:
                ast = self._actor_state(msg.actor_id)
                with ast.lock:
                    ast.direct_addr = tuple(addr)
            self.controller.set_actor_state(msg.actor_id, ALIVE, node_id)
        else:
            cause = "creation failed"
            if msg.error is not None and msg.error[0] == "err":
                try:
                    exc = serialization.unpack_payload(msg.error[1])
                    inner = getattr(exc, "cause", exc)
                    cause = f"creation failed: {type(inner).__name__}: {inner}"
                except Exception as e:
                    telemetry.note_swallowed("runtime.error_repr", e)
            self.controller.set_actor_state(msg.actor_id, DEAD,
                                            death_cause=cause)
            ast = self._actor_state(msg.actor_id)
            with ast.lock:
                pending = ast.pending_bind + list(ast.ready_buffer.values())
                ast.pending_bind = []
                ast.ready_buffer.clear()
            err = msg.error or ("err", serialization.pack_payload(
                ActorError(msg.actor_id, cause)))
            for spec, _a, _k in pending:
                for oid in spec.return_ids:
                    self.mark_ready(oid, err)

    # -- worker-initiated requests -------------------------------------- #

    def on_get_request(self, node, msg: GetRequest) -> None:
        states = self._states(msg.object_ids)
        remaining = {"n": len(states)}
        lock = threading.Lock()
        replied = {"done": False}
        timer_box: Dict[str, Any] = {}
        is_remote = getattr(node, "is_remote", False)
        is_client = getattr(node, "is_client", False)

        def finish(timed_out: bool):
            with lock:
                if replied["done"]:
                    return
                replied["done"] = True
            # The timeout Timer must die WITH the request: un-cancelled
            # it idles out the full user timeout per get() — thousands of
            # zombie timer threads under load (leak found by the
            # sanitizer).
            t = timer_box.get("t")
            if t is not None:
                t.cancel()
            if not is_remote and any(
                    isinstance(st.desc, tuple) and st.desc
                    and st.desc[0] == "at" for st in states
                    if st.ready):
                # Local reader needs remote objects: the pull blocks, so
                # run the reply construction on the transfer thread.
                self._offload(lambda: _build_reply(timed_out))
            else:
                _build_reply(timed_out)

        def _build_reply(timed_out: bool):
            values = []
            pinned_keys = []
            for oid, st in zip(msg.object_ids, states):
                if not st.ready:
                    values.append(("err", b""))
                    continue
                d = st.desc
                if is_remote:
                    # Consumer is on another node: it pulls payloads over
                    # the data plane by key, so ship location-tagged
                    # descriptors instead of pinning here (the fetch pins
                    # on the owner for the duration of the copy).
                    if isinstance(d, tuple) and d and d[0] in ("shm", "shma"):
                        from .cluster import tag_desc
                        d = tag_desc(d, self.node_id.binary())
                    values.append(d)
                    continue
                if isinstance(d, tuple) and d and d[0] == "at":
                    # Remote object requested by a head-local worker: pull
                    # it into the head store, then hand out a local pin.
                    d = self._puller.localize(d) if self._puller else (
                        "err", serialization.pack_payload(ObjectLostError(
                            "remote object without a cluster data plane",
                            object_id_bytes=oid.binary())))
                if is_client:
                    # Store-less remote driver: materialize to a raw inline
                    # payload (shm offsets mean nothing across the wire).
                    if isinstance(d, tuple) and d and d[0] in ("shm", "shma"):
                        from .cluster import read_raw_payload
                        raw = read_raw_payload(node.store, d)
                        d = ("inline", raw) if raw is not None else (
                            "err", serialization.pack_payload(ObjectLostError(
                                "object was evicted or freed",
                                object_id_bytes=oid.binary())))
                    values.append(d)
                    continue
                if isinstance(d, tuple) and d and d[0] == "shma":
                    # Refresh + pin so the offset stays valid until the
                    # worker's ReadDone (plasma client-pin semantics).
                    nd = node.store.pin_desc_by_key(d[4])
                    if nd is None:
                        d = ("err", serialization.pack_payload(
                            ObjectLostError("object was evicted or freed",
                                            object_id_bytes=oid.binary())))
                    else:
                        d = nd
                        pinned_keys.append(nd[4])
                values.append(d)
            if pinned_keys:
                node.track_get_pins(msg.worker_id, msg.request_id,
                                    pinned_keys)
            node.send_to_worker(msg.worker_id,
                                GetReply(msg.request_id, values, timed_out))

        def one_ready():
            with lock:
                remaining["n"] -= 1
                done = remaining["n"] == 0
            if done:
                finish(False)

        if msg.timeout_s is not None:
            timer = threading.Timer(msg.timeout_s, lambda: finish(True))
            timer.daemon = True
            timer_box["t"] = timer
            timer.start()
        if not states:
            finish(False)
        for st in states:
            st.add_callback(one_ready)

    def on_wait_request(self, node: NodeManager, msg: WaitRequest) -> None:
        def run():
            try:
                ready, _ = self.wait(msg.object_ids, msg.num_returns,
                                     msg.timeout_s)
            except Exception:  # noqa: BLE001 — a lost reply hangs the caller
                ready = []
            node.send_to_worker(msg.worker_id,
                                WaitReply(msg.request_id, ready))
        sanitizer.spawn(run, name="wait-reply")

    def on_put_from_worker(self, msg: PutFromWorker) -> None:
        self.mark_ready(msg.object_id, msg.desc)

    # ctl_* methods that may block (long-poll style): handled off the
    # reader thread so one waiting worker can't stall its node connection.
    # stack_dump/debug_dump wait for StackDumpReplies that arrive ON the
    # poller thread — running them there would deadlock the collection.
    _BLOCKING_CTL = frozenset({"kv_wait", "pubsub_poll", "stack_dump",
                               "debug_dump", "profile"})

    def on_rpc_call(self, node, msg: RpcCall) -> None:
        def run():
            try:
                fn = getattr(self, "ctl_" + msg.method)
                value = fn(*msg.args, **msg.kwargs)
                node.send_to_worker(msg.worker_id,
                                    RpcReply(msg.request_id, value))
            except Exception as e:  # noqa: BLE001
                node.send_to_worker(msg.worker_id,
                                    RpcReply(msg.request_id, None, repr(e)))
        if msg.method in self._BLOCKING_CTL:
            sanitizer.spawn(run, name=f"ctl-{msg.method}")
        else:
            run()

    # control-plane methods callable from workers (and used by the driver
    # API directly). All arguments/returns must be plain picklable data.

    def ctl_pin_object(self, oid_bytes: bytes) -> bool:
        """Pin an object against eviction AND reference-count collection
        (ray_tpu.checkpoint emergency replicas: the newest snapshot must
        survive object-store pressure and the producer dropping its ref).
        Returns whether the head store held a pinnable copy; either way
        the escape-mark keeps the directory entry alive."""
        oid = ObjectID(oid_bytes)
        self.mark_escaped(oid)
        sanitizer.note_pin(oid.hex())
        store_pin = getattr(self.node.store, "try_pin", None)
        if store_pin is None:
            return False
        return bool(store_pin(oid, pinner="ckpt_pin"))

    def ctl_unpin_object(self, oid_bytes: bytes) -> bool:
        oid = ObjectID(oid_bytes)
        with self._ref_lock:
            self._escaped.discard(oid)
        sanitizer.note_unpin(oid.hex())
        store_unpin = getattr(self.node.store, "try_unpin", None)
        if store_unpin is None:
            return False
        return bool(store_unpin(oid, pinner="ckpt_pin"))

    def ctl_kv_put(self, key, value, namespace="default", overwrite=True):
        return self.controller.kv_put(key, value, namespace, overwrite)

    def ctl_kv_get(self, key, namespace="default"):
        return self.controller.kv_get(key, namespace)

    def ctl_kv_del(self, key, namespace="default"):
        return self.controller.kv_del(key, namespace)

    def ctl_kv_keys(self, prefix="", namespace="default"):
        return self.controller.kv_keys(prefix, namespace)

    def ctl_kv_wait(self, key, namespace="default", timeout=None):
        return self.controller.kv_wait(key, namespace, timeout)

    def ctl_get_named_actor(self, name, namespace=None):
        info = self.controller.get_named_actor(name,
                                               namespace or self.namespace)
        if info is None or info.state == DEAD:
            return None
        return (info.actor_id.binary(), info.max_restarts, info.class_name)

    def ctl_register_actor(self, actor_id_bytes, name, namespace, max_restarts,
                           class_name):
        info = ActorInfo(ActorID(actor_id_bytes), name or None,
                         "DEPENDENCIES_UNREADY", None, max_restarts,
                         namespace=namespace or self.namespace,
                         class_name=class_name)
        self.register_actor(info)
        if name:
            sanitizer.note_named_actor(name, namespace or self.namespace,
                                       class_name)
        return True

    def ctl_actor_creation_spec(self, actor_id_bytes, spec: TaskSpec):
        info = self.controller.get_actor(ActorID(actor_id_bytes))
        if info is not None:
            info.creation_spec = spec
            # Re-persist: the creation spec is what a restarted head
            # rebuilds the actor from.
            self.controller._p(("actor", info))
        return True

    def ctl_kill_actor(self, actor_id_bytes, no_restart=True):
        self.kill_actor(ActorID(actor_id_bytes), no_restart)
        return True

    def ctl_actor_state(self, actor_id_bytes):
        info = self.controller.get_actor(ActorID(actor_id_bytes))
        return info.state if info else None

    def ctl_create_pg(self, bundles: List[Dict[str, float]], strategy: str,
                      name: Optional[str] = None):
        from .controller import BundleInfo
        pg_id = PlacementGroupID.of(self.job_id)
        info = PlacementGroupInfo(
            pg_id, name, strategy,
            [BundleInfo(i, ResourceSet(b)) for i, b in enumerate(bundles)])
        self.controller.register_placement_group(info)
        self.scheduler.create_placement_group(info)
        return pg_id.binary()

    def ctl_pg_state(self, pg_id_bytes):
        info = self.controller.get_placement_group(PlacementGroupID(pg_id_bytes))
        return info.state if info else None

    def ctl_pg_bundle_locations(self, pg_id_bytes):
        info = self.controller.get_placement_group(PlacementGroupID(pg_id_bytes))
        if info is None:
            return None
        return [b.node_id.binary() if b.node_id else None for b in info.bundles]

    def ctl_remove_pg(self, pg_id_bytes):
        info = self.controller.get_placement_group(PlacementGroupID(pg_id_bytes))
        if info is not None:
            self.scheduler.remove_placement_group(info)
        return True

    def ctl_cluster_resources(self):
        return self.scheduler.total_resources()

    def ctl_available_resources(self):
        return self.scheduler.available_resources()

    def ctl_nodes(self):
        now = time.monotonic()
        return [{"node_id": n.node_id.hex(), "alive": n.alive,
                 "hostname": n.hostname,
                 "resources": n.total_resources.to_dict(),
                 "is_head": n.is_head,
                 "draining": n.draining,
                 "drain_reason": n.drain_reason,
                 # Relative, so cross-process readers never difference a
                 # foreign monotonic stamp (RT203 territory).
                 "drain_remaining_s": max(0.0, n.drain_deadline_mono - now)
                 if n.draining else 0.0}
                for n in self.controller.nodes.values()]

    def ctl_drain_node(self, node_id_hex: str, deadline_s: float = 30.0,
                       reason: str = "preemption") -> bool:
        """Drain protocol entry point: mark the node unschedulable for
        new leases and advertise the kill deadline.  Train/serve
        controllers poll the node table and evacuate their work; the
        autoscaler's provider hook and `ray-tpu drain` both land here."""
        try:
            node_id = NodeID.from_hex(node_id_hex)
        except ValueError:
            return False
        if not self.controller.drain_node(node_id, deadline_s, reason):
            return False
        self.scheduler.set_draining(node_id, True)
        telemetry.set_gauge("ray_tpu_node_draining",
                            len(self.controller.draining_nodes()))
        return True

    def ctl_undrain_node(self, node_id_hex: str) -> bool:
        try:
            node_id = NodeID.from_hex(node_id_hex)
        except ValueError:
            return False
        if not self.controller.undrain_node(node_id):
            return False
        self.scheduler.set_draining(node_id, False)
        telemetry.set_gauge("ray_tpu_node_draining",
                            len(self.controller.draining_nodes()))
        return True

    # -- syncer (reference: src/ray/ray_syncer/ray_syncer.h:91) -------------

    def on_node_view(self, node_id: NodeID, version: int, view: dict) -> None:
        """Receive a versioned resource view; stale versions are dropped
        (reference: ray_syncer receiver version check)."""
        with self._node_views_lock:
            cur = self._node_views.get(node_id)
            if cur is not None and cur[0] >= version:
                return
            self._node_views[node_id] = (version, view, time.time())

    def ctl_node_views(self):
        """Latest per-node load views; the head's own node is sampled live
        (it needs no sync channel)."""
        out = {}
        with self._node_views_lock:
            for nid, (version, view, ts) in self._node_views.items():
                out[nid.hex()] = dict(view, _version=version, _ts=ts)
        local = self.nodes.get(self.node_id)
        if local is not None and not getattr(local, "is_remote", False):
            out[self.node_id.hex()] = dict(local.local_view(),
                                           _version=-1, _ts=time.time())
        return out

    def ctl_list_actors(self, filters=None, limit=10000):
        """Actor table view; ``filters`` is an equality dict applied
        server-side so point lookups (state.get_actor) don't ship the
        whole table (mirrors ctl_list_tasks' filter pushdown)."""
        out = []
        for a in self.controller.actors.values():
            rec = {"actor_id": a.actor_id.hex(), "state": a.state,
                   "name": a.name, "class_name": a.class_name,
                   "num_restarts": a.num_restarts,
                   # Placement: lets drain-aware owners (train/serve
                   # controllers) find which of their actors sit on a
                   # draining node.
                   "node_id": a.node_id.hex() if a.node_id else None}
            if filters and any(rec.get(k) != v for k, v in filters.items()):
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return out

    # -- state API feeds (reference: dashboard/modules/state/state_head.py
    #    backed by GcsTaskManager; here the buffers live in-process) ----- #

    def ctl_resolve_actor_direct(self, actor_id_bytes: bytes):
        """Resolve an actor's direct-call address for a caller worker
        (reference: the GCS actor-table lookup the core worker does before
        opening its caller->actor stream).  Returns (state, addr, cause):
        state in {"alive", "pending", "restarting", "dead"}; addr is the
        worker's direct listener when alive (None if the worker predates
        direct serving or runs without a token)."""
        try:
            actor_id = ActorID(actor_id_bytes)
        except ValueError:
            return ("dead", None, "invalid actor id")
        info = self.controller.get_actor(actor_id)
        if info is None:
            return ("dead", None, "unknown actor")
        if info.state == DEAD:
            return ("dead", None, info.death_cause or "actor died")
        if info.state in (PENDING_CREATION, RESTARTING):
            return ("pending" if info.state == PENDING_CREATION
                    else "restarting", None, None)
        ast = self._actor_state(actor_id)
        with ast.lock:
            return ("alive", ast.direct_addr, None)

    def ctl_list_tasks(self, filters=None, limit=10000, stage=None,
                       min_stage_wait_s=None):
        """Task-event records with server-side pushdown: equality
        ``filters``, ``limit`` (newest-first early exit), and lifecycle
        stage-latency selection (``stage`` + ``min_stage_wait_s``) — a
        point lookup must stay cheap when the ring holds the 10k-node
        bench's task table."""
        return self.events.snapshot(filters, limit, stage,
                                    min_stage_wait_s)

    def ctl_summarize_tasks(self, states=None, limit=None):
        return self.events.summary(states, limit)

    # -- control-plane telescope (ray_tpu.schedview; reference analog:
    #    `ray status -v` demand debug strings, here first-class) -------- #

    def ctl_sched_stats(self):
        """Live scheduler view for `ray-tpu sched` / GET /api/sched:
        queue depths, decision totals + trailing rates, task-event
        buffer health (ring saturation), node counts."""
        self.scheduler._maybe_publish_metrics(force=True)
        ring = self.scheduler.ring
        return {
            "queues": self.scheduler.queue_depths(),
            "decisions": ring.stats(),
            "rates": {"decisions_per_s_5s": round(ring.rate(5.0), 2),
                      "decisions_per_s_60s": round(ring.rate(60.0), 2)},
            "events": self.events.stats(),
            "nodes": {"total": len(self.controller.nodes),
                      "draining": len(self.controller.draining_nodes())},
        }

    def ctl_sched_decisions(self, task_id=None, limit=200):
        """Recent scheduler decision records (bounded ring snapshot);
        ``task_id`` filters, prefix ok."""
        return self.scheduler.ring.snapshot(task_id, limit)

    def ctl_explain_task(self, task_id_hex: str):
        """Answer `ray-tpu task why <id>`: why is this task still
        pending (unresolved deps / closest-fit gap / drain fence /
        missing PG bundle), or why did it land where it did (the
        recorded placement decision).  Accepts id prefixes."""
        matches = {t.hex() for t in self.scheduler.pending_task_ids()
                   if t.hex().startswith(task_id_hex)}
        matches.update(self.events.find_ids(task_id_hex))
        if not matches:
            return {"task_id": task_id_hex, "status": "unknown",
                    "reasons": [],
                    "detail": "no task with this id (or prefix) in the "
                              "scheduler queues or the task-event ring"}
        if len(matches) > 1 and task_id_hex not in matches:
            return {"task_id": task_id_hex, "status": "ambiguous",
                    "reasons": [], "matches": sorted(matches)[:8]}
        tid_hex = task_id_hex if task_id_hex in matches \
            else next(iter(matches))
        out: Dict[str, Any] = {"task_id": tid_hex}
        ev = (self.events.snapshot({"task_id": tid_hex}, 1)
              or [None])[0]
        if ev is not None:
            out["state"] = ev["state"]
            out["name"] = ev["name"]
            out["stage_waits"] = ev["stage_waits"]
            out["node_id"] = ev["node_id"]
            if ev["error_message"]:
                out["error_message"] = ev["error_message"]
        decision = self.scheduler.ring.latest_for(tid_hex)
        if decision is not None:
            out["last_decision"] = decision
        pending = None
        try:
            pending = self.scheduler.explain_task(TaskID.from_hex(tid_hex))
        except ValueError:
            pending = None
        if pending is not None:
            out.update(pending)
            return out
        # Not held by the scheduler: it placed (or never queued).
        state = out.get("state")
        out["status"] = {
            PENDING_ARGS: "submitted", READY: "ready", PLACED: "placed",
            SUBMITTED_TO_NODE: "dispatched", RUNNING: "running",
            FINISHED: "finished", FAILED: "failed",
        }.get(state, "unknown")
        out.setdefault("reasons", [])
        return out

    @staticmethod
    def _desc_location(desc, local_hex):
        """(node_hex, inner_desc, nbytes) for a directory descriptor; a
        bare descriptor lives on the head, an "at" tag names its owner."""
        if not desc:
            return None, None, None
        node_hex, inner = local_hex, desc
        if desc[0] == "at":
            node_hex, inner = desc[1].hex(), desc[2]
        nbytes = None
        if inner[0] == "inline":
            nbytes = len(inner[1])
        elif inner[0] == "shm":
            nbytes = inner[2]
        elif inner[0] == "shma":
            nbytes = inner[3]
        return node_hex, inner, nbytes

    def ctl_list_objects(self, limit=10000):
        ring = getattr(self.node.store, "view", None)
        latest = {}
        if ring is not None:
            for rec in ring.latest_index():
                latest[rec["object_id"]] = rec
        out = []
        with self._dir_lock:
            items = list(self.directory.items())[:limit]
        local_hex = self.node_id.hex()
        for oid, st in items:
            desc = st.desc
            kind = desc[0] if desc else "pending"
            node_hex, _inner, nbytes = self._desc_location(desc, local_hex)
            rec = {"object_id": oid.hex(), "status": kind,
                   "size_bytes": nbytes, "node_id": node_hex,
                   "task_id": oid.task_id().hex()}
            seen = latest.get(oid.hex())
            if seen is not None:
                rec["store_state"] = seen["state"]
                rec["pins"] = seen["pins"]
            out.append(rec)
        return out

    # -- data-plane telescope (storeview): memory summary, per-object
    #    explain, store event ring — reference: `ray memory`, the
    #    memory_summary state API ---------------------------------------- #

    def ctl_memory_summary(self, top_n: int = 10):
        """Cluster-wide object-store occupancy: per-node stats (the head
        sampled live, remote nodes via their synced views), directory-
        attributed top objects by size, and leak candidates.  Backs
        `ray-tpu memory` and state.memory_summary()."""
        self._publish_store_metrics(force=True)
        nodes = {}
        for nhex, view in self.ctl_node_views().items():
            sub = view.get("store")
            if isinstance(sub, dict):
                nodes[nhex] = dict(sub)
        totals = {}
        for key in ("used_bytes", "capacity_bytes", "pinned_bytes",
                    "spilled_bytes", "num_objects", "num_pinned",
                    "num_spilled"):
            totals[key] = sum(int(sub.get(key, 0))
                              for sub in nodes.values())
        objects = self.ctl_list_objects()
        sized = [o for o in objects if o.get("size_bytes")]
        sized.sort(key=lambda o: o["size_bytes"], reverse=True)
        leaks = []
        for nhex, sub in nodes.items():
            for rec in sub.get("leak_candidates") or ():
                leaks.append(dict(rec, node_id=nhex))
        leaks.sort(key=lambda r: int(r.get("nbytes", 0)), reverse=True)
        return {"nodes": nodes, "totals": totals,
                "top_objects": sized[:top_n],
                "leak_candidates": leaks,
                "num_directory_objects": len(objects)}

    def ctl_explain_object(self, object_id_hex: str):
        """Answer `ray-tpu obj why <id>`: where an object lives (directory
        descriptor + owner node), what produced it (owner task id from the
        id itself), and what the store event ring saw it do (spill/restore
        and pull history, pins and pinners).  Accepts id prefixes."""
        prefix = (object_id_hex or "").lower()
        with self._dir_lock:
            matches = [oid for oid in self.directory
                       if oid.hex().startswith(prefix)]
        ring = getattr(self.node.store, "view", None)
        if not matches:
            # Deleted objects leave the directory but linger in the
            # ring's latest-state index: still explainable.
            if ring is not None:
                rec = ring.explain(prefix)
                if rec.get("status") in ("ok", "ambiguous"):
                    rec.setdefault("directory", None)
                    return rec
            return {"object_id": prefix, "status": "unknown",
                    "detail": "no object with this id (or prefix) in the "
                              "directory or the store event ring"}
        hexes = sorted(o.hex() for o in matches)
        if len(matches) > 1 and prefix not in hexes:
            return {"object_id": prefix, "status": "ambiguous",
                    "matches": hexes[:8]}
        oid = matches[0] if len(matches) == 1 \
            else next(o for o in matches if o.hex() == prefix)
        with self._dir_lock:
            st = self.directory.get(oid)
        desc = st.desc if st is not None else None
        node_hex, inner, nbytes = self._desc_location(desc,
                                                      self.node_id.hex())
        out: Dict[str, Any] = {
            "object_id": oid.hex(), "status": "ok",
            "owner_task_id": oid.task_id().hex(),
            "directory": {"state": desc[0] if desc else "pending",
                          "node_id": node_hex, "size_bytes": nbytes,
                          "error": bool(inner) and inner[0] == "err"}}
        if ring is not None:
            rec = ring.explain(oid.hex())
            out["local"] = rec if rec.get("status") == "ok" else None
        if node_hex and node_hex != self.node_id.hex():
            # Remote object: its lifecycle lives in the owner's ring; the
            # synced store view carries that node's top objects, so
            # surface a match when one exists.
            view = self.ctl_node_views().get(node_hex) or {}
            sub = view.get("store") or {}
            for ent in sub.get("top_objects") or ():
                if ent.get("object_id") == oid.hex():
                    out["owner_view"] = ent
                    break
        return out

    def ctl_store_events(self, object_id=None, limit=200):
        """Head store event-ring snapshot (newest-last); feeds the
        flight-recorder bundle and tests."""
        ring = getattr(self.node.store, "view", None)
        if ring is None:
            return {"events": [], "stats": {}}
        return {"events": ring.snapshot(object_id, limit),
                "stats": ring.stats()}

    def ctl_list_placement_groups(self):
        return [{"placement_group_id": pg.pg_id.hex(), "state": pg.state,
                 "name": pg.name, "strategy": pg.strategy,
                 "bundle_count": len(pg.bundles)}
                for pg in self.controller.placement_groups.values()]

    def ctl_list_jobs(self):
        return [{"job_id": j.job_id.hex(), "start_time": j.start_time,
                 "end_time": j.end_time, "entrypoint": j.entrypoint}
                for j in self.controller.jobs.values()]

    def ctl_get_fn_blob(self, fn_id: bytes):
        return self._fn_table.get(fn_id)

    # -- live diagnostics (reference: `ray stack`, scripts.py; the debug
    #    state dump a postmortem attaches) ------------------------------- #

    def on_stack_reply(self, msg, node_id: Optional[NodeID] = None) -> None:
        """A worker's StackDumpReply landed (local poller thread or a
        remote node's UpStackReply): file it under its dump id."""
        with self._stack_lock:
            entry = self._stack_dumps.get(msg.dump_id)
            if entry is None:
                return  # collector already timed out and left
            record = dict(msg.record)
            record["node_id"] = node_id.hex() if node_id is not None else None
            entry["replies"][msg.worker_id.hex()] = record
            evt = entry["event"]
        evt.set()

    def on_stack_expect(self, dump_id: int, worker_ids: List) -> None:
        """A remote node answered StackDumpAll with the worker set it
        fanned out to: widen the expected-reply set so a wedged remote
        worker surfaces as 'unresponsive' instead of silently missing."""
        with self._stack_lock:
            entry = self._stack_dumps.get(dump_id)
            if entry is None:
                return
            entry["want"].update(w.hex() for w in worker_ids)
            entry["expects_pending"] -= 1
            evt = entry["event"]
        evt.set()

    def ctl_stack_dump(self,
                       timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Snapshot every live worker's thread stacks plus the driver's
        own (cluster-wide ``ray stack``).  Returns ``{"time", "stacks",
        "unresponsive"}``; a worker that cannot answer within the timeout
        is itself a diagnostic signal and is listed by id.

        Blocking: listed in _BLOCKING_CTL so a worker-originated call
        never runs on the node poller thread that must route the replies.
        """
        from .diagnostics import capture_process_stacks
        if timeout_s is None:
            timeout_s = Config.get("stack_dump_timeout_s")
        nodes = list(self.nodes.values())
        remote_nodes = [n for n in nodes if getattr(n, "is_remote", False)]
        with self._stack_lock:
            self._stack_dump_seq += 1
            dump_id = self._stack_dump_seq
            # Each remote node answers the broadcast with an UpStackExpect
            # naming its worker set; until every expect has landed the
            # collection can't know it has seen all wanted replies.
            entry: Dict[str, Any] = {"replies": {}, "want": set(),
                                     "expects_pending": len(remote_nodes),
                                     "event": threading.Event()}
            self._stack_dumps[dump_id] = entry
        expected: List[WorkerID] = []
        for node in nodes:
            try:
                ids = node.broadcast_stack_dump(dump_id)
                if not getattr(node, "is_remote", False):
                    expected.extend(ids)
            except Exception:  # noqa: BLE001 — a dead node can't stop a dump
                with self._stack_lock:
                    if getattr(node, "is_remote", False):
                        entry["expects_pending"] -= 1
        with self._stack_lock:
            entry["want"].update(w.hex() for w in expected)
        self._settle_collect(entry, timeout_s)
        with self._stack_lock:
            self._stack_dumps.pop(dump_id, None)
            replies = dict(entry["replies"])
            want = set(entry["want"])
        driver = capture_process_stacks("driver", is_driver=True)
        driver["node_id"] = self.node_id.hex()
        stacks = [driver] + [replies[k] for k in sorted(replies)]
        return {"time": time.time(), "stacks": stacks,
                "unresponsive": sorted(want - set(replies))}

    def _settle_collect(self, entry: Dict[str, Any], timeout_s: float,
                        settle_s: float = 0.5) -> None:
        """Wait for a broadcast collection (stack dump / profile) to
        complete: every wanted reply present AND every remote node's
        expect set landed — or replies stopped arriving for
        ``settle_s`` (a node server that dies before answering with its
        expect set must not hold the collection to the full timeout)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        last_change = time.monotonic()
        prev_progress = -1
        while time.monotonic() < deadline:
            with self._stack_lock:
                have = set(entry["replies"])
                want = set(entry["want"])
                expects_pending = entry["expects_pending"]
            progress = len(have) + len(want)
            if progress != prev_progress:
                prev_progress = progress
                last_change = time.monotonic()
            if want <= have and (
                    expects_pending <= 0
                    or time.monotonic() - last_change >= settle_s):
                break
            entry["event"].clear()
            entry["event"].wait(min(0.05, max(
                0.0, deadline - time.monotonic())))

    # -- cluster profiler (see ray_tpu/profiler/) ------------------------ #

    def on_profile_reply(self, msg, node_id: Optional[NodeID] = None
                         ) -> None:
        """A worker's ProfileReply landed (local node or a remote's
        UpProfileReply): file it under its profile id."""
        with self._stack_lock:
            entry = self._profiles.get(msg.profile_id)
            if entry is None:
                return  # collector already timed out and left
            record = dict(msg.record)
            record["node_id"] = node_id.hex() if node_id is not None \
                else None
            entry["replies"][msg.worker_id.hex()] = record
            evt = entry["event"]
        evt.set()

    def on_profile_expect(self, profile_id: int, worker_ids: List) -> None:
        """A remote node answered ProfileAll with its worker set (see
        on_stack_expect — wedged remote workers must surface as
        unresponsive)."""
        with self._stack_lock:
            entry = self._profiles.get(profile_id)
            if entry is None:
                return
            entry["want"].update(w.hex() for w in worker_ids)
            entry["expects_pending"] -= 1
            evt = entry["event"]
        evt.set()

    def ctl_profile(self, duration_s: float = 2.0, hz: float = 67.0,
                    jax_profile: bool = False,
                    timeout_s: Optional[float] = None,
                    save: bool = True) -> Dict[str, Any]:
        """Cluster-wide on-demand profile: every live worker (plus the
        driver) samples its threads for ``duration_s``; the records are
        merged into ONE clock-aligned Chrome-trace JSON written under
        ``<session>/profiles/`` and returned inline.

        Blocking for duration + collection timeout: listed in
        _BLOCKING_CTL so a worker-originated call never runs on the
        node poller thread that must route the replies."""
        from ray_tpu.profiler.capture import capture_profile
        from ray_tpu.profiler.merge import (merge_records, write_jax_artifacts,
                                            write_trace)
        from .protocol import ProfileRequest
        if timeout_s is None:
            timeout_s = Config.get("stack_dump_timeout_s")
        duration_s = max(0.1, float(duration_s))
        nodes = list(self.nodes.values())
        remote_nodes = [n for n in nodes if getattr(n, "is_remote", False)]
        t0_wall = time.time()
        with self._stack_lock:
            self._profile_seq += 1
            profile_id = self._profile_seq
            entry: Dict[str, Any] = {"replies": {}, "want": set(),
                                     "expects_pending": len(remote_nodes),
                                     "event": threading.Event()}
            self._profiles[profile_id] = entry
        req = ProfileRequest(profile_id, duration_s, hz=hz,
                             jax_profile=jax_profile,
                             driver_wall_s=t0_wall)
        expected: List[WorkerID] = []
        for node in nodes:
            try:
                ids = node.broadcast_profile(req)
                if not getattr(node, "is_remote", False):
                    expected.extend(ids)
            except Exception:  # noqa: BLE001 — a dead node can't stop it
                with self._stack_lock:
                    if getattr(node, "is_remote", False):
                        entry["expects_pending"] -= 1
        with self._stack_lock:
            entry["want"].update(w.hex() for w in expected)
        # The driver samples itself on THIS thread (ctl_profile is
        # blocking-listed) while the workers capture in parallel.
        driver_record = capture_profile(
            "driver", duration_s, hz=hz, jax_profile=jax_profile,
            driver_wall_s=t0_wall, is_driver=True)
        self._settle_collect(entry, timeout_s)
        with self._stack_lock:
            self._profiles.pop(profile_id, None)
            replies = dict(entry["replies"])
            want = set(entry["want"])
        t1_wall = time.time()
        records = [driver_record] + [replies[k] for k in sorted(replies)]
        doc = merge_records(
            records,
            timeline_events=self.events.chrome_trace(),
            # Wall clock on purpose: the window selects timeline events
            # by their wall-anchored positions, not a duration.
            window=(t0_wall - 1.0, t1_wall + 1.0),  # ray-tpu: noqa[RT203]
            meta={"profile_id": profile_id, "duration_s": duration_s,
                  "hz": hz, "driver_t0_wall_s": t0_wall,
                  "unresponsive": sorted(want - set(replies))})
        path = None
        if save:
            pdir = os.path.join(self.session_dir, "profiles",
                                f"{time.strftime('%Y%m%d-%H%M%S')}-"
                                f"{profile_id:04d}")
            path = write_trace(os.path.join(pdir, "trace.json"), doc)
            write_jax_artifacts(pdir, records)
        telemetry.inc("ray_tpu_profiler_captures_total")
        return {
            "path": path,
            "trace": doc,
            "num_events": len(doc["traceEvents"]),
            "workers": sorted(replies),
            "unresponsive": sorted(want - set(replies)),
        }

    def ctl_debug_dump(self, reason: str = "manual",
                       capture_stacks: bool = True,
                       extra: Optional[Dict[str, Any]] = None,
                       profile_s: Optional[float] = None) -> str:
        """Write a postmortem bundle under <session>/debug/; returns its
        path (flight recorder, `ray-tpu debug dump`).  ``profile_s`` > 0
        attaches an on-demand cluster profile of that duration (None =
        the debug_bundle_profile_s config default)."""
        from .diagnostics import write_debug_bundle
        return write_debug_bundle(self, reason,
                                  capture_stacks=capture_stacks,
                                  extra=extra, profile_s=profile_s)

    def ctl_export_event(self, source_type: str, event: Dict[str, Any]):
        """Append a structured record to <session>/logs/events.jsonl on
        behalf of any process (train watchdog, user tooling)."""
        self._export_event(source_type, dict(event))
        return True

    def _export_event(self, source_type: str, event: Dict[str, Any]) -> None:
        try:
            self.export_events.write(source_type, event)
        except Exception as e:  # forensics never fail the caller
            telemetry.note_swallowed("runtime.export_event", e)

    def _maybe_death_bundle(self, reason: str,
                            extra: Dict[str, Any]) -> None:
        """Rate-limited flight-recorder capture on unexpected worker death
        (no stack broadcast: the dead worker can't answer, and the bundle
        must stay cheap on the failure path)."""
        if self._shutdown or not Config.get("debug_bundle_on_worker_death"):
            return
        now = time.monotonic()
        if self._last_death_bundle is not None and \
                now - self._last_death_bundle < Config.get(
                "debug_bundle_min_interval_s"):
            return
        self._last_death_bundle = now

        def run():
            try:
                from .diagnostics import write_debug_bundle
                write_debug_bundle(self, reason, capture_stacks=False,
                                   extra=extra)
            except Exception as e:
                telemetry.note_swallowed("runtime.death_bundle", e)
        sanitizer.spawn(run, name="death-bundle")

    # -- pubsub (reference: src/ray/pubsub/ long-poll publisher) ----------

    def ctl_publish(self, channel: str, message) -> None:
        self.controller.publish(channel, message)

    def ctl_pubsub_poll(self, channel: str, after_seq: int = 0,
                        timeout=None):
        return self.controller.pubsub_poll(channel, after_seq, timeout)

    def ctl_log_files(self):
        """Session log files + sizes (reference: state API list_logs)."""
        return self.log_monitor.list_files()

    def ctl_log_tail(self, filename: str, n: int = 100):
        """Last n lines of a session log file (reference: state API
        get_log)."""
        return self.log_monitor.tail(filename, n)

    def ctl_session_dir(self):
        return self.session_dir

    def ctl_timeline(self):
        return self.events.chrome_trace()

    def ctl_add_profile_span(self, name, category, start_s, end_s, pid, tid,
                             extra=None):
        self.events.add_span(
            ProfileSpan(name, category, start_s, end_s, pid, tid, extra))
        return True

    _STORE_OP_KINDS = ("create", "seal", "get", "pin", "unpin", "delete")
    _STORE_SPILL_KEYS = (("spill", "num_spilled"),
                         ("restore", "num_restored"),
                         ("evict", "num_evictions"))

    def _store_metrics_state(self):
        state = getattr(self, "_store_pub", None)
        if state is None:
            state = self._store_pub = {"lock": threading.Lock(),
                                       "last": 0.0, "counts": {}}
        return state

    def _publish_store_metrics(self, force: bool = False) -> None:
        """Data-plane half of the telemetry flush: fold per-node object
        store occupancy into head-registry gauges and turn event-ring /
        stats tallies into counter deltas.  Piggybacks on the existing
        metrics flush (no second reporting loop) and is rate-limited so a
        busy cluster's flush storms don't rescan the views every push.
        Counter deltas are clamped at zero: a node that restarts resets
        its tallies, and a negative delta must not decrement a counter."""
        pub = self._store_metrics_state()
        now = time.monotonic()
        with pub["lock"]:
            if not force and now - pub["last"] < 1.0:
                return
            pub["last"] = now
        try:
            head = dict(self.node.store.stats())
            ring = getattr(self.node.store, "view", None)
            if ring is not None:
                head["counts"] = dict(ring.counts)
            per_node = {self.node_id.hex(): head}
            with self._node_views_lock:
                views = [(nid.hex(), view) for nid, (_v, view, _ts)
                         in self._node_views.items()]
            for nhex, view in views:
                sub = view.get("store")
                if isinstance(sub, dict):
                    per_node[nhex] = sub
            for nhex, sub in per_node.items():
                tags = {"node": nhex}
                telemetry.set_gauge("ray_tpu_store_used_bytes",
                                    int(sub.get("used_bytes", 0)), tags=tags)
                telemetry.set_gauge("ray_tpu_store_capacity_bytes",
                                    int(sub.get("capacity_bytes", 0)),
                                    tags=tags)
                telemetry.set_gauge("ray_tpu_store_pinned_bytes",
                                    int(sub.get("pinned_bytes", 0)),
                                    tags=tags)
                telemetry.set_gauge("ray_tpu_store_spilled_bytes",
                                    int(sub.get("spilled_bytes", 0)),
                                    tags=tags)
                telemetry.set_gauge("ray_tpu_store_objects",
                                    int(sub.get("num_objects", 0)),
                                    tags=tags)
                prev = pub["counts"].setdefault(nhex, {})
                counts = sub.get("counts") or {}
                for kind in self._STORE_OP_KINDS:
                    cur = int(counts.get(kind, 0))
                    delta = cur - prev.get(kind, 0)
                    if delta > 0:
                        telemetry.inc("ray_tpu_store_ops_total", delta,
                                      tags={"op": kind})
                    prev[kind] = cur
                for op, key in self._STORE_SPILL_KEYS:
                    cur = int(sub.get(key, 0))
                    delta = cur - prev.get("_" + op, 0)
                    if delta > 0:
                        telemetry.inc("ray_tpu_store_spill_ops_total",
                                      delta, tags={"op": op})
                    prev["_" + op] = cur
                # Remote nodes' transfers happen in THEIR processes:
                # _record_transfer incs a registry the merged scrape
                # never sees, so the bytes ride the synced ring tallies
                # instead.  The head's own entry is skipped — its
                # transfers already inc'd in-process (double count).
                if nhex == self.node_id.hex():
                    continue
                tb = sub.get("transfer_bytes") or {}
                for direction in ("push", "pull"):
                    cur = int(tb.get(direction, 0))
                    delta = cur - prev.get("_tb_" + direction, 0)
                    if delta > 0:
                        telemetry.inc(
                            "ray_tpu_store_transfer_bytes_total",
                            delta, tags={"direction": direction})
                    prev["_tb_" + direction] = cur
        except Exception as e:  # noqa: BLE001
            telemetry.note_swallowed("runtime.store_metrics", e)

    def ctl_metrics_push(self, source_id: str, snapshot):
        """One batched per-process metrics flush (util/metrics.py flush
        paths).  Stores the latest snapshot for the merged scrape AND
        gives the time-series backplane its ingest tick — piggybacked
        here so history needs no second reporting loop."""
        self.metrics_snapshots[source_id] = snapshot
        self._publish_store_metrics()
        self.metricsview.on_push()
        return True

    # Back-compat verb name (pre-metricsview workers).
    ctl_push_metrics = ctl_metrics_push

    def ctl_metrics_query(self, name: str, window_s: float = 60.0,
                          agg: str = "avg", tags=None):
        # Give the store gauges a flush chance first: a driver-only
        # session has no worker pushes to piggyback on.
        self._publish_store_metrics()
        return self.metricsview.query(name, window_s, agg, tags=tags)

    def ctl_metrics_history(self, name: str, window_s: float = 300.0,
                            tags=None, max_points: int = 240):
        return self.metricsview.history(name, window_s, tags=tags,
                                        max_points=max_points)

    def ctl_metrics_series(self):
        return self.metricsview.store.series_names()

    def ctl_alerts(self, recent: int = 50):
        return self.metricsview.alerts(recent=recent)

    def ctl_slo_set(self, objectives):
        return self.metricsview.set_objectives(objectives)

    def ctl_slo_list(self):
        return self.metricsview.slo.objectives()

    # -- tracing (reference: util/tracing/tracing_helper.py spans routed
    #    to a collector; here an in-memory bounded span table) ----------- #

    def ctl_add_trace_span(self, span: dict):
        buf = getattr(self, "_trace_spans", None)
        if buf is None:
            from collections import deque
            buf = self._trace_spans = deque(maxlen=50_000)
        buf.append(span)
        return True

    def ctl_get_trace_spans(self, trace_id=None):
        buf = getattr(self, "_trace_spans", None) or ()
        return [s for s in buf
                if trace_id is None or s.get("trace_id") == trace_id]

    def ctl_list_trace_ids(self):
        buf = getattr(self, "_trace_spans", None) or ()
        seen = dict.fromkeys(s.get("trace_id") for s in buf)
        return list(seen)

    # ------------------------------------------------------------------ #

    def shutdown(self) -> None:
        self._shutdown = True
        self.scheduler.stop()
        with self._actors_lock:
            asts = list(self._actors.values())
        for ast in asts:
            if ast.driver_ch is not None:
                ast.driver_ch.close()
        if self._gc_enabled:
            self._ref_drop_q.put(None)
        if self._xfer_q is not None:
            self._xfer_q.put(None)
            self._xfer_pool.shutdown(wait=False)
        if self.head_server is not None:
            self.head_server.shutdown()
        if self.data_server is not None:
            self.data_server.shutdown()
        if self._data_client is not None:
            self._data_client.shutdown()
        self.node.shutdown()
        if self.state_store is not None:
            # Clean shutdown: actors die with the cluster — only a CRASHED
            # head revives actors on restart.  Without this, a later
            # unrelated `start --head` on the same state dir would re-run
            # stale user actor code from the snapshot.
            try:
                for info in list(self.controller.actors.values()):
                    if info.state != DEAD:
                        self.controller.set_actor_state(
                            info.actor_id, DEAD,
                            death_cause="cluster shutdown")
                # Compact so the next start replays a snapshot instead of
                # the whole WAL.
                self.state_store.compact(self.controller.snapshot_records())
            except Exception as e:
                telemetry.note_swallowed("runtime.shutdown_compact", e)
            self.state_store.close()
        self.log_monitor.stop()
        self.log_monitor.poll_once()  # flush buffered worker output
        self.export_events.close()
        for shm in self._mapped_segments.values():
            try:
                shm.close()
            except Exception:  # ray-tpu: noqa[RT202] — best-effort teardown
                pass
        self._mapped_segments.clear()
        self.controller.finish_job(self.job_id)
        global _global_runtime
        with _runtime_lock:
            if _global_runtime is self:
                _global_runtime = None


def init_runtime(**kwargs) -> Runtime:
    global _global_runtime
    with _runtime_lock:
        if _global_runtime is not None:
            return _global_runtime
        # Leak-sanitizer baseline BEFORE the Runtime boots: the
        # runtime's own long-lived threads (ref-gc, head-accept,
        # node-dispatch, ...) must be inside the gate — a regression
        # that leaves one running after shutdown() is exactly what the
        # ratchet exists to catch (RAY_TPU_SANITIZE=1).
        sanitizer.snapshot()
        rt = Runtime(**kwargs)
        _global_runtime = rt
    return rt
