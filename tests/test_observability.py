"""State API, task events, user metrics, timeline tests.

Reference analogs: python/ray/tests/test_state_api.py, test_metrics_agent.py,
test_task_events.py.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import state as state_api


@ray_tpu.remote
def quick(x):
    return x + 1


@ray_tpu.remote
def failing():
    raise RuntimeError("intentional")


@ray_tpu.remote
class StatefulThing:
    def ping(self):
        return "pong"


class TestStateAPI:
    def test_list_tasks_records_lifecycle(self, ray_start):
        ref = quick.remote(1)
        assert ray_tpu.get(ref) == 2
        time.sleep(0.1)
        tasks = state_api.list_tasks()
        mine = [t for t in tasks if t["name"].startswith("quick")]
        assert mine, f"no quick task in {tasks[:3]}"
        done = [t for t in mine if t["state"] == "FINISHED"]
        assert done
        ev = done[-1]
        assert ev["state_times"].get("RUNNING") is not None
        assert ev["state_times"]["FINISHED"] >= ev["state_times"]["RUNNING"]

    def test_failed_task_records_error(self, ray_start):
        ref = failing.remote()
        with pytest.raises(Exception):
            ray_tpu.get(ref)
        time.sleep(0.1)
        failed = state_api.list_tasks(filters=[("state", "=", "FAILED")])
        assert any("intentional" in (t["error_message"] or "")
                   for t in failed)

    def test_list_actors_and_summary(self, ray_start):
        h = StatefulThing.remote()
        assert ray_tpu.get(h.ping.remote()) == "pong"
        actors = state_api.list_actors()
        assert any(a["class_name"] == "StatefulThing" and a["state"] == "ALIVE"
                   for a in actors)
        summary = state_api.summarize_actors()
        assert summary.get("StatefulThing", {}).get("ALIVE", 0) >= 1

    def test_list_nodes_objects_jobs_pgs(self, ray_start):
        ref = ray_tpu.put(b"x" * 10)
        nodes = state_api.list_nodes()
        assert nodes and nodes[0]["is_head"]
        objects = state_api.list_objects()
        assert any(o["object_id"] == ref.hex() for o in objects)
        jobs = state_api.list_jobs()
        assert len(jobs) >= 1
        pg = ray_tpu.placement_group([{"CPU": 1}])
        assert pg.ready(timeout=10)
        pgs = state_api.list_placement_groups()
        assert any(p["placement_group_id"] == pg.id.hex() for p in pgs)
        ray_tpu.remove_placement_group(pg)

    def test_summarize_tasks(self, ray_start):
        ray_tpu.get([quick.remote(i) for i in range(3)])
        time.sleep(0.1)
        summary = state_api.summarize_tasks()
        q = [v for k, v in summary.items() if k.startswith("quick")]
        assert q and q[0].get("FINISHED", 0) >= 3

    def test_state_api_from_worker(self, ray_start):
        @ray_tpu.remote
        def introspect():
            from ray_tpu.util import state
            return len(state.list_nodes())

        assert ray_tpu.get(introspect.remote()) >= 1


class TestTimeline:
    def test_timeline_chrome_trace(self, ray_start, tmp_path):
        ray_tpu.get([quick.remote(i) for i in range(2)])
        time.sleep(0.1)
        out = tmp_path / "trace.json"
        payload = ray_tpu.timeline(str(out))
        trace = json.loads(payload)
        assert isinstance(trace, list) and trace
        ev = [e for e in trace if e["ph"] == "X" and e["cat"] == "task"]
        assert ev
        assert {"name", "ts", "dur", "pid", "tid"} <= set(ev[0])
        assert json.loads(out.read_text()) == trace


class TestProfileSpan:
    def test_user_span_in_timeline(self, ray_start):
        with state_api.profile_span("my_phase", category="demo"):
            time.sleep(0.01)
        trace = json.loads(ray_tpu.timeline())
        spans = [e for e in trace if e["name"] == "my_phase"]
        assert spans and spans[0]["cat"] == "demo"
        assert spans[0]["dur"] >= 10_000  # >= 10ms in microseconds

    def test_span_from_worker(self, ray_start):
        @ray_tpu.remote
        def traced():
            from ray_tpu.util import state
            with state.profile_span("inner_work"):
                time.sleep(0.01)
            return True

        assert ray_tpu.get(traced.remote())
        trace = json.loads(ray_tpu.timeline())
        assert any(e["name"] == "inner_work" for e in trace)


class TestMetrics:
    def setup_method(self):
        metrics_mod._reset_for_tests()

    def test_counter_gauge_histogram(self, ray_start):
        c = metrics_mod.Counter("test_requests_total", "reqs",
                                tag_keys=("route",))
        c.inc(tags={"route": "/a"})
        c.inc(2.0, tags={"route": "/a"})
        g = metrics_mod.Gauge("test_queue_depth", "depth")
        g.set(7)
        h = metrics_mod.Histogram("test_latency_s", "lat",
                                  boundaries=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = metrics_mod.prometheus_text()
        assert 'test_requests_total{route="/a"} 3.0' in text
        assert "test_queue_depth 7.0" in text
        assert 'test_latency_s_bucket{le="0.1"} 1.0' in text
        assert 'test_latency_s_bucket{le="+Inf"} 3.0' in text
        assert "test_latency_s_count 3.0" in text
        assert "# TYPE test_requests_total counter" in text

    def test_counter_validation(self, ray_start):
        c = metrics_mod.Counter("test_val_total", tag_keys=("k",))
        with pytest.raises(ValueError):
            c.inc(-1)
        with pytest.raises(ValueError):
            c.inc(tags={"bogus": "x"})

    def test_metrics_http_server(self, ray_start):
        metrics_mod.Gauge("test_http_gauge").set(1.5)
        port = metrics_mod.start_metrics_server(0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "test_http_gauge 1.5" in body

    def test_stop_metrics_server_releases_listener(self, ray_start):
        metrics_mod.Gauge("test_stop_gauge").set(2.0)
        port = metrics_mod.start_metrics_server(0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "test_stop_gauge 2.0" in body
        metrics_mod.stop_metrics_server()
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=2)
        # Idempotent (and safe from the reset path).
        metrics_mod.stop_metrics_server()

    def test_task_final_metrics_flush_deterministic(self, ray_start):
        """Metrics recorded just before a task finishes are at the driver
        the moment the task is observed complete — no 2 s flusher race,
        no explicit flush() in the task."""
        @ray_tpu.remote
        def last_gasp():
            from ray_tpu.util import metrics
            metrics.Counter("test_last_gasp_total").inc(3.0)
            return True  # exits well inside the flusher's 2 s window

        assert ray_tpu.get(last_gasp.remote(), timeout=60)
        assert "test_last_gasp_total 3.0" in metrics_mod.prometheus_text()

    def test_worker_metrics_flow_to_driver(self, ray_start):
        @ray_tpu.remote
        def work():
            from ray_tpu.util import metrics
            c = metrics.Counter("test_worker_side_total")
            c.inc(5.0)
            metrics.flush()
            return True

        assert ray_tpu.get(work.remote())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if "test_worker_side_total 5.0" in metrics_mod.prometheus_text():
                break
            time.sleep(0.2)
        assert "test_worker_side_total 5.0" in metrics_mod.prometheus_text()


class TestTracing:
    """W3C trace-context propagation through task submission (reference:
    python/ray/util/tracing/tracing_helper.py:34,181)."""

    def test_driver_task_nested_task_one_tree(self, ray_start_isolated):
        import ray_tpu
        from ray_tpu.util import tracing

        @ray_tpu.remote
        def inner(x):
            return x * 2

        @ray_tpu.remote
        def outer(x):
            return ray_tpu.get(inner.remote(x)) + 1

        tracing.enable()
        try:
            assert ray_tpu.get(outer.remote(20), timeout=60) == 41
        finally:
            tracing.disable()

        # Give the workers' span RPCs a moment to land.
        import time as _t
        deadline = _t.monotonic() + 20
        spans = []
        while _t.monotonic() < deadline:
            ids = tracing.list_traces()
            if ids:
                spans = tracing.get_trace(ids[0])
                if len(spans) >= 4:
                    break
            _t.sleep(0.2)
        names = [s["name"] for s in spans]
        assert "submit outer" in names and "execute outer" in names
        assert "submit inner" in names and "execute inner" in names
        # One trace id across the whole cascade.
        assert len({s["trace_id"] for s in spans}) == 1
        by_id = {s["span_id"]: s for s in spans}
        sub_inner = next(s for s in spans if s["name"] == "submit inner")
        exec_outer = next(s for s in spans if s["name"] == "execute outer")
        # The nested submit is a child of the outer execute span.
        assert sub_inner["parent_span_id"] == exec_outer["span_id"]
        # The outer execute chains to the driver's submit span.
        sub_outer = next(s for s in spans if s["name"] == "submit outer")
        assert exec_outer["parent_span_id"] == sub_outer["span_id"]
        assert sub_outer["parent_span_id"] is None
        # The tree renders with every span on its own line.
        txt = tracing.render_trace(spans[0]["trace_id"])
        assert txt.count("- ") >= 4

    def test_actor_method_cascade_shares_trace(self, ray_start_isolated):
        """Actor-method calls propagate the W3C context exactly like plain
        tasks: driver -> actor method -> nested task is ONE trace tree."""
        import ray_tpu
        from ray_tpu.util import tracing

        @ray_tpu.remote
        def leaf(x):
            return x + 1

        @ray_tpu.remote
        class Middle:
            def call(self, x):
                return ray_tpu.get(leaf.remote(x)) * 2

        tracing.enable()
        try:
            h = Middle.remote()
            assert ray_tpu.get(h.call.remote(1), timeout=60) == 4
        finally:
            tracing.disable()

        import time as _t
        deadline = _t.monotonic() + 20
        spans = []
        while _t.monotonic() < deadline:
            ids = tracing.list_traces()
            for tid in ids:
                got = tracing.get_trace(tid)
                if any("Middle.call" in s["name"] for s in got):
                    spans = got
            if len(spans) >= 4:
                break
            _t.sleep(0.2)
        names = [s["name"] for s in spans]
        assert "submit Middle.call" in names, names
        assert "execute Middle.call" in names
        assert "submit leaf" in names and "execute leaf" in names
        # The whole cascade shares one trace id.
        assert len({s["trace_id"] for s in spans}) == 1
        exec_call = next(s for s in spans
                         if s["name"] == "execute Middle.call")
        sub_call = next(s for s in spans
                        if s["name"] == "submit Middle.call")
        sub_leaf = next(s for s in spans if s["name"] == "submit leaf")
        exec_leaf = next(s for s in spans if s["name"] == "execute leaf")
        # Nested submit inside the actor method chains to its execute
        # span; the method execute chains to the driver's submit.
        assert sub_leaf["parent_span_id"] == exec_call["span_id"]
        assert exec_call["parent_span_id"] == sub_call["span_id"]
        assert exec_leaf["parent_span_id"] == sub_leaf["span_id"]
        assert sub_call["parent_span_id"] is None

    def test_otlp_json_export(self, ray_start_isolated, tmp_path):
        import json

        import ray_tpu
        from ray_tpu.util import tracing

        @ray_tpu.remote
        def f():
            return 1

        tracing.enable()
        try:
            ray_tpu.get(f.remote(), timeout=60)
        finally:
            tracing.disable()
        out = tracing.export_otlp_json(str(tmp_path / "trace.json"))
        doc = json.load(open(out))
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert spans and all(s["traceId"] and s["spanId"] for s in spans)

    def test_tracing_disabled_adds_no_context(self, ray_start_isolated):
        import ray_tpu
        from ray_tpu.util import tracing

        @ray_tpu.remote
        def f():
            return 1

        assert not tracing.is_enabled()
        ray_tpu.get(f.remote(), timeout=60)
        assert tracing.list_traces() == []


class TestOtlpMetricsExport:
    def test_export_shape(self, ray_start, tmp_path):
        """OTLP/JSON resourceMetrics export (reference: the OTel metrics
        exporter behind open_telemetry_metric_recorder.h)."""
        import json

        from ray_tpu.util import metrics as m
        c = m.Counter("otlp_test_total", "d", tag_keys=("k",))
        c.inc(3, tags={"k": "a"})
        g = m.Gauge("otlp_test_gauge")
        g.set(7.5)
        h = m.Histogram("otlp_test_hist", boundaries=[1.0, 10.0])
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)

        path = m.export_otlp_json(str(tmp_path / "metrics.json"))
        doc = json.load(open(path))
        scope = doc["resourceMetrics"][0]["scopeMetrics"][0]
        by_name = {mm["name"]: mm for mm in scope["metrics"]}
        s = by_name["otlp_test_total"]["sum"]
        assert s["isMonotonic"] and s["dataPoints"][0]["asDouble"] == 3.0
        assert by_name["otlp_test_gauge"]["gauge"]["dataPoints"][0][
            "asDouble"] == 7.5
        hist = by_name["otlp_test_hist"]["histogram"]["dataPoints"][0]
        assert hist["count"] == "3" and hist["sum"] == 55.5
        assert hist["explicitBounds"] == [1.0, 10.0]
        assert hist["bucketCounts"] == ["1", "1", "1"]
