"""Framework-internal lint rules (RT2xx): invariants of ray_tpu itself.

These run only on files inside the ``ray_tpu`` package tree (the
self-lint gate in tests/test_lint.py keeps the tree clean), and on
snippets linted with ``internal=True``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .lint import (Finding, ModuleContext, Rule, dotted, register,
                   walk_same_scope)

#: A with-target whose dotted name's last segment matches this is
#: treated as a mutex for RT201.
_LOCKISH_RE = re.compile(r"(lock|cond|mutex|cv)", re.IGNORECASE)

#: Modules where a swallowed exception hides scheduler/runtime state
#: corruption (RT202).  Matched as a suffix of the normalized path.
CONTROL_PLANE_MODULES = (
    "_private/runtime.py",
    "_private/scheduler.py",
    "_private/node.py",
)

#: Attribute calls that block the calling thread (RT201).
_BLOCKING_ATTRS = {"recv", "recv_bytes", "accept", "communicate",
                   "check_call", "check_output", "result"}
_BLOCKING_DOTTED = {"time.sleep", "select.select", "subprocess.run",
                    "subprocess.call", "subprocess.check_call",
                    "subprocess.check_output"}


def _condition_locks(ctx: ModuleContext) -> Dict[str, str]:
    """``cond name -> lock name`` for ``X = threading.Condition(Y)``
    assignments: waiting on X while holding Y is the *correct* condition
    idiom (wait releases Y), so RT201 must not flag it."""
    out: Dict[str, str] = {}
    for node in ctx.nodes(ast.Assign):
        v = node.value
        if isinstance(v, ast.Call) and \
                (dotted(v.func) or "").endswith("Condition") and v.args:
            lock = dotted(v.args[0])
            if lock:
                for t in node.targets:
                    name = dotted(t)
                    if name:
                        out[name] = lock
    return out


def _is_str_join(call: ast.Call) -> bool:
    """Distinguish ``sep.join(iterable)`` from ``thread.join(timeout)``:
    flag only zero-arg joins, numeric-literal timeouts, or a ``timeout=``
    keyword — the unambiguous thread/process forms."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    if not call.args and not call.keywords:
        return False
    if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, (int, float)):
        return False
    return True


@register
class BlockingUnderLock(Rule):
    id = "RT201"
    example_bad = (
        "with self._lock:\n"
        "    time.sleep(1.0)     # every contender convoys\n")
    example_good = (
        "with self._lock:\n"
        "    work = self._take()\n"
        "time.sleep(1.0)         # block after releasing\n")
    scope = "internal"
    summary = "blocking call while holding a lock"
    rationale = ("A sleep/join/recv/wait/subprocess call under a held "
                 "lock stalls every thread contending for it — the "
                 "classic control-plane convoy; release the lock before "
                 "blocking, or use a Condition on that lock.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        cond_locks = _condition_locks(ctx)
        for node in ctx.nodes(ast.With, ast.AsyncWith):
            lock_names: Set[str] = set()
            for item in node.items:
                name = dotted(item.context_expr)
                if name and _LOCKISH_RE.search(name.split(".")[-1]):
                    lock_names.add(name)
            if not lock_names:
                continue
            for sub in walk_same_scope(node):
                if not isinstance(sub, ast.Call):
                    continue
                label = self._blocking_label(sub, lock_names, cond_locks)
                if label:
                    held = ", ".join(sorted(lock_names))
                    # Suppressible at the call line or the with line (a
                    # lock that intentionally serializes slow work gets
                    # one noqa on the with statement).
                    yield ctx.finding(
                        self, sub,
                        f"{label} while holding {held}: blocking under a "
                        f"lock convoys every contending thread",
                        anchors=(node,))

    def _blocking_label(self, call: ast.Call, lock_names: Set[str],
                        cond_locks: Dict[str, str]) -> Optional[str]:
        name = dotted(call.func)
        if name in _BLOCKING_DOTTED:
            return f"{name}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = dotted(call.func.value)
        if attr in ("wait", "wait_for"):
            # Waiting on the condition guarding this very lock is the
            # idiom (wait releases the lock); waiting on anything else
            # (an Event, another lock's condition) blocks while held.
            if recv in lock_names:
                return None
            if recv and cond_locks.get(recv) in lock_names:
                return None
            return f"{recv or attr}.{attr}()" if recv else f"{attr}()"
        if attr == "join":
            if _is_str_join(call):
                return None
            return f"{recv or '<expr>'}.join()"
        if attr in _BLOCKING_ATTRS:
            return f"{recv or '<expr>'}.{attr}()"
        return None


@register
class SwallowedException(Rule):
    id = "RT202"
    example_bad = (
        "try:\n"
        "    handler(msg)\n"
        "except Exception:\n"
        "    pass                 # state corruption hides\n")
    example_good = (
        "try:\n"
        "    handler(msg)\n"
        "except Exception as e:\n"
        "    telemetry.note_swallowed(\"runtime.handler\", e)\n")
    scope = "internal"
    summary = "bare `except Exception: pass` in a control-plane module"
    rationale = ("A silently swallowed control-plane error hides state "
                 "corruption until an unrelated hang; log it or bump "
                 "ray_tpu_internal_swallowed_errors_total "
                 "(telemetry.note_swallowed).")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.module_key.endswith(CONTROL_PLANE_MODULES):
            return
        for node in ctx.nodes(ast.Try):
            for handler in node.handlers:
                t = handler.type
                broad = t is None or (
                    isinstance(t, ast.Name) and
                    t.id in ("Exception", "BaseException"))
                if not broad:
                    continue
                body = [s for s in handler.body
                        if not (isinstance(s, ast.Expr) and
                                isinstance(s.value, ast.Constant))]
                if all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in body):
                    yield ctx.finding(
                        self, handler,
                        "swallowed exception in a control-plane module: "
                        "log it or call telemetry.note_swallowed(where)")


@register
class WallClockDuration(Rule):
    id = "RT203"
    example_bad = (
        "t0 = time.time()\n"
        "work()\n"
        "elapsed = time.time() - t0   # NTP step corrupts it\n")
    example_good = (
        "t0 = time.monotonic()\n"
        "work()\n"
        "elapsed = time.monotonic() - t0\n")
    scope = "internal"
    summary = "duration arithmetic on time.time()"
    rationale = ("Wall clocks step under NTP; intervals, deadlines and "
                 "timeouts must come from time.monotonic().  time.time() "
                 "stays correct for timestamps that are only recorded.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "time.time" not in ctx.source:
            return  # the rule is about literal time.time() call sites
        scopes: List[ast.AST] = [ctx.tree]
        scopes += ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)
        for scope in scopes:
            tainted = self._tainted_names(scope)
            for node in walk_same_scope(scope):
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub):
                    if self._is_wall(node.left, tainted) or \
                            self._is_wall(node.right, tainted):
                        yield ctx.finding(
                            self, node,
                            "interval computed from time.time(): use "
                            "time.monotonic() (NTP steps corrupt "
                            "wall-clock arithmetic)")
                elif isinstance(node, ast.Compare):
                    ops_ok = all(isinstance(op, (ast.Lt, ast.LtE, ast.Gt,
                                                 ast.GtE))
                                 for op in node.ops)
                    sides = [node.left] + list(node.comparators)
                    if ops_ok and any(self._is_wall(s, tainted)
                                      for s in sides):
                        yield ctx.finding(
                            self, node,
                            "deadline comparison on time.time(): use "
                            "time.monotonic()")

    @staticmethod
    def _tainted_names(scope: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in walk_same_scope(scope):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    dotted(node.value.func) == "time.time":
                out |= {t.id for t in node.targets
                        if isinstance(t, ast.Name)}
        return out

    @staticmethod
    def _is_wall(node: ast.AST, tainted: Set[str]) -> bool:
        if isinstance(node, ast.Call) and dotted(node.func) == "time.time":
            return True
        return isinstance(node, ast.Name) and node.id in tainted


@register
class UnknownTelemetrySeries(Rule):
    id = "RT204"
    example_bad = (
        "telemetry.inc(\"ray_tpu_misspelled_total\")  # silently records nothing\n")
    example_good = (
        "# declare the series in util/telemetry.py CATALOG first\n"
        "telemetry.inc(\"ray_tpu_serve_requests_total\")\n")
    scope = "internal"
    summary = "telemetry series name missing from the catalog"
    rationale = ("util/telemetry.py's CATALOG is the single source of "
                 "truth for built-in series; a name minted at a call "
                 "site silently records nothing (inc/observe/set_gauge "
                 "swallow the KeyError).")

    _FNS = {"inc", "observe", "set_gauge", "counter", "gauge", "histogram"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "telemetry" not in ctx.source:
            return  # any alias/import spells the word somewhere
        try:
            from ray_tpu.util.telemetry import CATALOG
        except Exception:  # not importable from this checkout: skip
            return
        aliases, direct = self._telemetry_names(ctx)
        if not aliases and not direct:
            return
        for node in ctx.nodes(ast.Call):
            if not node.args:
                continue
            fn = None
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in aliases and \
                    node.func.attr in self._FNS:
                fn = node.func.attr
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in direct:
                fn = direct[node.func.id]
            if fn is None:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    arg.value not in CATALOG:
                yield ctx.finding(
                    self, node,
                    f"telemetry.{fn}({arg.value!r}): not in the "
                    f"util/telemetry.py CATALOG — declare it there or "
                    f"fix the name")

    @staticmethod
    def _telemetry_names(ctx: ModuleContext):
        aliases: Set[str] = set()
        direct: Dict[str, str] = {}
        for node in ctx.nodes(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.endswith("telemetry"):
                        aliases.add(a.asname or a.name.split(".")[-1])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.endswith("util") or mod.endswith("ray_tpu.util"):
                    for a in node.names:
                        if a.name == "telemetry":
                            aliases.add(a.asname or "telemetry")
                elif mod.endswith("telemetry"):
                    for a in node.names:
                        if a.name in UnknownTelemetrySeries._FNS:
                            direct[a.asname or a.name] = a.name
        return aliases, direct


#: Modules whose on-disk files other processes treat as commit records
#: (RT206): a torn write here IS state corruption, so every publication
#: must be tmp-file + os.replace.  Matched against the normalized path.
_ATOMIC_PUBLISH_MODULES = (
    "/checkpoint/",            # the distributed checkpointing subsystem
    "train/_checkpoint.py",    # its compat shim
    "_private/persist.py",     # head-state WAL/snapshot store
)


@register
class NonAtomicPublish(Rule):
    id = "RT206"
    example_bad = (
        "with open(manifest_path, \"w\") as f:   # torn prefix on crash\n"
        "    json.dump(doc, f)\n")
    example_good = (
        "write_bytes_atomic(manifest_path,\n"
        "                   json.dumps(doc).encode())  # tmp + os.replace\n")
    scope = "internal"
    summary = "non-atomic file publication in a checkpoint/control-plane " \
              "module"
    rationale = ("A manifest/index written with a bare open(path, 'w') can "
                 "be observed (or survive a crash) as a torn prefix that "
                 "parses as a valid-looking file; publish through a tmp "
                 "file + os.replace (checkpoint.format.write_bytes_atomic) "
                 "so the path either holds the full bytes or nothing.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        key = ctx.module_key
        if not (any(key.endswith(m) for m in _ATOMIC_PUBLISH_MODULES
                    if not m.startswith("/"))
                or any(m in key for m in _ATOMIC_PUBLISH_MODULES
                       if m.startswith("/"))):
            return
        for node in ctx.nodes(ast.Call):
            if dotted(node.func) not in ("open", "io.open") or \
                    not node.args:
                continue
            mode = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "mode"),
                None)
            if not (isinstance(mode, ast.Constant) and
                    isinstance(mode.value, str) and
                    mode.value.startswith("w")):
                continue
            # The tmp+replace idiom names its staging path: a path
            # expression mentioning "tmp" (tmp var, .tmp suffix,
            # mkstemp/mkdtemp product) is the atomic pattern's first
            # half, not a publication.
            path_src = ast.unparse(node.args[0])
            if "tmp" in path_src.lower():
                continue
            yield ctx.finding(
                self, node,
                f"open({path_src}, {mode.value!r}) publishes a file "
                f"non-atomically: write to a tmp path and os.replace() "
                f"into place (see checkpoint.format.write_bytes_atomic)")


@register
class DevicePutAliasedHostBuffer(Rule):
    id = "RT207"
    example_bad = (
        "buf = np.zeros((8, 128))\n"
        "x = jax.device_put(buf, sharding)\n"
        "buf[0] = 1.0   # mutates the device value it aliases\n")
    example_good = (
        "buf = np.zeros((8, 128))\n"
        "x = jax.device_put(buf.copy(), sharding)\n"
        "buf[0] = 1.0   # device copy is independent\n")
    scope = "internal"
    summary = "jax.device_put of a host buffer mutated in the same scope"
    rationale = ("On CPU (and zero-copy shm-store views) jax.device_put "
                 "may alias the host ndarray instead of copying; an "
                 "in-place write to that buffer after dispatch silently "
                 "corrupts the device value (the mesh/pipeline dispatch "
                 "aliasing hazard).  Pass a real copy (.copy()) — NOT "
                 "np.ascontiguousarray, which returns the SAME object "
                 "for an already-contiguous buffer — or stop mutating "
                 "the buffer.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "device_put" not in ctx.source:
            return
        # Scope: any module in a jax dispatch context — inferred from
        # the shared RT5xx jax-context detection (imports of jax /
        # jax.numpy / jax.random, or the lazy `self._jax` handle) —
        # instead of the old hard-coded directory list.
        from .rules_jax import module_uses_jax
        if not module_uses_jax(ctx):
            return
        scopes: List[ast.AST] = [ctx.tree]
        scopes += ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef)
        for scope in scopes:
            mutated = self._mutated_lines(scope)
            if not mutated:
                continue
            for node in walk_same_scope(scope):
                if not (isinstance(node, ast.Call) and
                        (dotted(node.func) or "").endswith("device_put")
                        and node.args):
                    continue
                arg = node.args[0]
                # Only mutations AFTER the dispatch can corrupt the
                # device value; fill-then-dispatch is the normal safe
                # init pattern.  (Line order approximates execution
                # order: a loop that mutates textually above a dispatch
                # inside it is not caught — keep dispatches out of
                # mutate-loops anyway.)
                if isinstance(arg, ast.Name) and any(
                        line > node.lineno
                        for line in mutated.get(arg.id, ())):
                    yield ctx.finding(
                        self, node,
                        f"jax.device_put({arg.id!r}) of a host buffer "
                        f"mutated after dispatch: device_put may alias "
                        f"instead of copy — dispatch a real copy "
                        f"({arg.id}.copy(); ascontiguousarray does NOT "
                        f"copy contiguous buffers)")

    @staticmethod
    def _mutated_lines(scope: ast.AST) -> Dict[str, List[int]]:
        """Line numbers of in-place writes per name: subscript-store
        targets (``buf[i] = ...``) and augmented assignments
        (``buf += ...`` / ``buf[i] += ...``).  Rebinding (``buf = ...``)
        is NOT mutation — the old buffer the device aliased is
        unchanged."""
        out: Dict[str, List[int]] = {}
        for node in walk_same_scope(scope):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                name = None
                if isinstance(t, ast.Subscript):
                    name = dotted(t.value)
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(t, ast.Name):
                    name = t.id
                if name:
                    out.setdefault(name, []).append(node.lineno)
        return out


@register
class ProtocolHandlerMissing(Rule):
    id = "RT205"
    example_bad = (
        "@dataclass\n"
        "class NewMessage:      # declared in protocol.py...\n"
        "    x: int = 0\n"
        "# ...but no isinstance(msg, NewMessage) handler anywhere\n")
    example_good = (
        "# in worker.py/node.py/runtime.py/cluster.py:\n"
        "if isinstance(msg, NewMessage):\n"
        "    handle_new_message(msg)\n")
    scope = "internal"
    summary = "protocol message type with no registered handler"
    rationale = ("Every dataclass in _private/protocol.py must be "
                 "dispatched via isinstance() in worker.py / node.py / "
                 "runtime.py / cluster.py; an unhandled type is either "
                 "dead wire surface or a message that silently drops.")

    #: Payload structs carried inside other messages, not dispatched.
    EXEMPT = {"TaskSpec"}
    HANDLER_MODULES = ("worker.py", "node.py", "runtime.py", "cluster.py")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.module_key.endswith("_private/protocol.py"):
            return
        declared: Dict[str, ast.ClassDef] = {
            node.name: node for node in ctx.tree.body
            if isinstance(node, ast.ClassDef) and
            node.name not in self.EXEMPT}
        handled = self.handled_names(os.path.dirname(ctx.path))
        if handled is None:
            return  # snippet with no sibling handler files: skip
        for name, node in declared.items():
            if name not in handled:
                yield ctx.finding(
                    self, node,
                    f"protocol message {name} has no isinstance() "
                    f"handler in {'/'.join(self.HANDLER_MODULES)}: wire "
                    f"it up or delete the message type")

    @classmethod
    def handled_names(cls, private_dir: str) -> Optional[Set[str]]:
        """Class names appearing as an isinstance() classinfo in any
        handler module (shared with tests/test_protocol_coverage.py)."""
        out: Set[str] = set()
        found_any = False
        for fname in cls.HANDLER_MODULES:
            path = os.path.join(private_dir, fname)
            if not os.path.exists(path):
                continue
            found_any = True
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Name) and
                        node.func.id == "isinstance" and
                        len(node.args) == 2):
                    continue
                info = node.args[1]
                names = info.elts if isinstance(info, ast.Tuple) else [info]
                out |= {n.id for n in names if isinstance(n, ast.Name)}
        return out if found_any else None
