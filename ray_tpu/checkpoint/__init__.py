"""ray_tpu.checkpoint — distributed checkpointing subsystem.

Async sharded saves (the train step blocks only for the device->host
snapshot), per-rank shard layout with an atomically committed global
manifest, resharding restore across world sizes, and optional emergency
in-memory replicas for fast single-worker-failure recovery.  See the
README "Checkpointing" section for the layout and semantics.
"""

from .async_writer import AsyncCheckpointWriter, WriteJob, publish_shard
from .format import (CheckpointError, Snapshot, build_manifest, build_shard,
                     commit_manifest, is_committed, load_pytree,
                     read_manifest, restore_tree, save_pytree, snapshot_tree,
                     verify_checkpoint, write_bytes_atomic, write_shard)
from .manager import (Checkpoint, CheckpointManager, WorkerCheckpointClient,
                      atomic_rmtree, scan_run_dir, step_dir)
from .replica import ReplicaHolder, ensure_holder, get_holder, holder_name
from .sharding import (even_placement, even_shard, even_shard_spec,
                       full_index, intersect, normalize_index)

__all__ = [
    "AsyncCheckpointWriter", "WriteJob", "publish_shard",
    "CheckpointError", "Snapshot",
    "build_manifest", "build_shard", "commit_manifest", "is_committed",
    "load_pytree", "read_manifest", "restore_tree", "save_pytree",
    "snapshot_tree", "verify_checkpoint", "write_bytes_atomic",
    "write_shard", "Checkpoint", "CheckpointManager",
    "WorkerCheckpointClient", "atomic_rmtree", "scan_run_dir", "step_dir",
    "ReplicaHolder", "ensure_holder", "get_holder", "holder_name",
    "even_placement", "even_shard", "even_shard_spec", "full_index",
    "intersect", "normalize_index",
]
