"""Test fixtures.

TPU-less CI substrate (SURVEY §4.2): jax collective/SPMD tests run on a
virtual 8-device CPU mesh via XLA host-platform device multiplexing — the
same technique the reference uses for TPU-logic tests without hardware
(reference: python/ray/tests/accelerators/test_tpu.py mocks env/metadata).
The env vars must be set before the first jax import anywhere in the process.
"""

import os
import sys

# The axon sitecustomize registers the TPU backend at interpreter boot, so
# env vars set here are too late for an already-started process — re-exec
# pytest once with the CPU-mesh environment (8 virtual devices).
def _invoked_as_pytest_cli() -> bool:
    """Only re-exec when argv really is a pytest command line — under
    pytest.main() from a host program, argv belongs to the host."""
    argv0 = os.path.basename(sys.argv[0] or "")
    return ("pytest" in argv0 or "py.test" in argv0
            or ("pytest" in sys.argv[0] and argv0 == "__main__.py"))


if not os.environ.get("RAY_TPU_TEST_REAL_TPU") \
        and not os.environ.get("_RAY_TPU_TEST_REEXEC") \
        and _invoked_as_pytest_cli():
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS=_flags, _RAY_TPU_TEST_REEXEC="1")
    try:
        # Pytest's fd-level capture is already active; restore the real
        # stdout/stderr so the re-exec'd run's output reaches the caller.
        import gc
        from _pytest.capture import CaptureManager
        for _obj in gc.get_objects():
            if isinstance(_obj, CaptureManager):
                _obj.stop_global_capturing()
                break
    except Exception:
        pass
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="module")
def ray_start():
    """Module-scoped runtime (reference: conftest ray_start_regular)."""
    import ray_tpu
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_isolated():
    """Function-scoped runtime for tests that mutate cluster state."""
    import ray_tpu
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()
