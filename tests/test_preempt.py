"""Preemption-aware elastic training: drain protocol, restart
hardening, chaos SLA.

Covers the graceful half of elasticity end to end: the signal plane
(``ctl_drain_node`` -> unschedulable node), the train drain path (urgent
checkpoint flush -> planned downsize booking ~0 lost work), serve
replica evacuation, the restart-hardening knobs (rolling failure
window, bounded backoff, crash-loop circuit breaker), and the tier-1
drain SLA: under the same chaos schedule, a graceful drain loses <= 25%
of the work an ungraceful kill loses.
"""

from __future__ import annotations

import tempfile
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.api import _control
from ray_tpu.cluster_utils import Cluster
from ray_tpu.devtools.chaos import ChaosRunner, ChaosSchedule
from ray_tpu.train import (CheckpointConfig, CrashLoopError, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)

WORKER_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
              "XLA_FLAGS": ""}


# -- signal plane -----------------------------------------------------------


class TestDrainSignalPlane:
    def test_drain_makes_node_unschedulable_and_undrain_reverts(
            self, ray_start_isolated):
        rt = ray_start_isolated
        nodes = _control("nodes")
        assert len(nodes) == 1
        hexid = nodes[0]["node_id"]
        assert nodes[0]["draining"] is False
        assert ray_tpu.available_resources().get("CPU", 0) > 0

        assert _control("drain_node", hexid, 30.0, "test-preempt") is True
        rec = next(n for n in _control("nodes") if n["node_id"] == hexid)
        assert rec["draining"] is True
        assert rec["drain_reason"] == "test-preempt"
        assert 0 < rec["drain_remaining_s"] <= 30.0
        # Schedulable capacity excludes the draining node entirely.
        assert ray_tpu.available_resources().get("CPU", 0) == 0

        # New leases don't land on it: a task submitted now stays queued.
        @ray_tpu.remote
        def probe():
            return "ran"

        ref = probe.remote()
        done, _ = ray_tpu.wait([ref], num_returns=1, timeout=1.0)
        assert not done, "task was scheduled onto a draining node"

        # Undrain lifts the fence and the queued task runs.
        assert _control("undrain_node", hexid) is True
        assert ray_tpu.get(ref, timeout=30) == "ran"
        rec = next(n for n in _control("nodes") if n["node_id"] == hexid)
        assert rec["draining"] is False
        assert rt is not None

    def test_drain_refuses_unknown_node(self, ray_start_isolated):
        assert _control("drain_node", "00" * 16, 10.0, "x") is False
        assert _control("drain_node", "not-hex", 10.0, "x") is False
        assert _control("undrain_node", "00" * 16) is False


class TestDrainRestSurface:
    def test_drain_endpoint_round_trip(self, ray_start_isolated):
        """The REST surface `ray-tpu drain` drives: POST drain -> node
        DRAINING in /api/cluster/status with remaining budget, POST
        undrain reverts, unknown node -> 404."""
        import json
        import urllib.error
        import urllib.request

        from ray_tpu.job_submission.manager import JobManager
        from ray_tpu.job_submission.server import JobServer

        server = JobServer(JobManager(), port=0)
        try:
            base = server.address

            def status_nodes():
                with urllib.request.urlopen(
                        base + "/api/cluster/status") as r:
                    return json.load(r)["nodes"]

            hexid = status_nodes()[0]["node_id"]
            req = urllib.request.Request(
                base + "/api/cluster/drain_node?node_id="
                + hexid + "&deadline_s=20&reason=resttest",
                method="POST")
            with urllib.request.urlopen(req) as r:
                assert json.load(r) == {"ok": True}
            rec = status_nodes()[0]
            assert rec["draining"] is True
            assert rec["drain_reason"] == "resttest"
            assert 0 < rec["drain_remaining_s"] <= 20.0
            req = urllib.request.Request(
                base + "/api/cluster/drain_node?node_id="
                + hexid + "&undrain=1", method="POST")
            with urllib.request.urlopen(req) as r:
                assert json.load(r) == {"ok": True}
            assert status_nodes()[0]["draining"] is False
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/api/cluster/drain_node?node_id=ffff",
                    method="POST"))
            assert ei.value.code == 404
        finally:
            server.stop()


# -- restart hardening ------------------------------------------------------


def _dying_train_fn(config):
    """Reports a couple of steps, then dies — every incarnation — until
    the marker directory has ``survive_after`` corpses."""
    import os
    import time as _t

    import ray_tpu.train as train

    marker_dir = config["marker_dir"]
    for step in range(3):
        _t.sleep(config.get("step_time", 0.05))
        train.report({"step": step + 1})
    deaths = len(os.listdir(marker_dir))
    if deaths < config["die_times"]:
        open(os.path.join(marker_dir, f"d{deaths}"), "w").close()
        if config.get("sleep_before_death_s"):
            _t.sleep(config["sleep_before_death_s"])
        os._exit(1)


def _raising_train_fn(config=None):
    import ray_tpu.train as train
    train.report({"step": 1})
    raise ValueError("deterministic bug: tensor shape mismatch")


class TestRestartHardening:
    def _trainer(self, fn, config, failure_config, tmp):
        return JaxTrainer(
            fn, train_loop_config=config,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="harden", storage_path=tmp,
                failure_config=failure_config))

    def test_failure_window_lets_spread_out_failures_pass(
            self, ray_start_isolated, tmp_path):
        """3 deaths with >~1.5s between them against max_failures=1 +
        failure_window_s=1.0: each failure ages out of the window before
        the next lands, so the run completes — where the lifetime
        counter would have killed it at death #2 (control case)."""
        marker = tmp_path / "m1"
        marker.mkdir()
        res = self._trainer(
            _dying_train_fn,
            {"marker_dir": str(marker), "die_times": 3,
             "step_time": 0.15, "sleep_before_death_s": 1.2},
            FailureConfig(max_failures=1, failure_window_s=1.0,
                          restart_backoff_initial_s=0.5,
                          restart_backoff_reset_s=0.0),
            str(tmp_path)).fit()
        assert res.error is None, res.error
        assert res.num_failures == 3  # total is still reported

        marker2 = tmp_path / "m2"
        marker2.mkdir()
        res2 = self._trainer(
            _dying_train_fn,
            {"marker_dir": str(marker2), "die_times": 3,
             "step_time": 0.15, "sleep_before_death_s": 1.2},
            FailureConfig(max_failures=1,
                          restart_backoff_initial_s=0.1),
            str(tmp_path)).fit()
        assert res2.error is not None  # lifetime budget: dead at #2
        assert res2.num_failures == 2

    def test_restart_backoff_is_bounded_exponential(
            self, ray_start_isolated, tmp_path):
        """Two restarts with initial=0.3 factor=2 cap=0.5: the observed
        backoff histogram must hold exactly [0.3, 0.5] (the second delay
        is CAPPED, not 0.6) — asserted from the telemetry series the
        catalog locks."""
        from ray_tpu.util import metrics as mmod

        def series(suffix):
            for line in mmod.prometheus_text().splitlines():
                if line.startswith(
                        "ray_tpu_train_restart_backoff_seconds" + suffix):
                    return float(line.split()[-1])
            return 0.0

        count0 = series("_count")
        sum0 = series("_sum")
        marker = tmp_path / "mb"
        marker.mkdir()
        res = self._trainer(
            _dying_train_fn,
            {"marker_dir": str(marker), "die_times": 2,
             "step_time": 0.05},
            FailureConfig(max_failures=2,
                          restart_backoff_initial_s=0.3,
                          restart_backoff_factor=2.0,
                          restart_backoff_max_s=0.5,
                          restart_backoff_reset_s=3600.0),
            str(tmp_path)).fit()
        assert res.error is None, res.error
        assert res.num_failures == 2
        assert series("_count") - count0 == 2
        assert series("_sum") - sum0 == pytest.approx(0.3 + 0.5, abs=0.01)

    def test_crash_loop_circuit_breaker_fails_fast_with_diagnosis(
            self, ray_start_isolated, tmp_path):
        """A deterministic exception recurring immediately must trip the
        breaker at the threshold — NOT burn the whole (large) failure
        budget — and surface a CrashLoopError naming the signature."""
        import os
        res = self._trainer(
            _raising_train_fn, None,
            FailureConfig(max_failures=50, crash_loop_threshold=2,
                          restart_backoff_initial_s=0.1),
            str(tmp_path)).fit()
        assert isinstance(res.error, CrashLoopError), res.error
        assert res.num_failures == 2  # threshold, not 51
        assert "ValueError" in res.error.signature
        assert "shape mismatch" in res.error.signature
        assert res.error.count == 2
        # The diagnosis bundle landed on disk with the crash-loop record.
        assert res.error.bundle_path and os.path.isdir(
            res.error.bundle_path)
        import json
        with open(os.path.join(res.error.bundle_path,
                               "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["extra"]["crash_loop"]["signature"] \
            == res.error.signature

    def test_formation_failure_is_restartable_not_fatal(
            self, ray_start_isolated, tmp_path, monkeypatch):
        """A group-formation crash (capacity vanished mid-formation) is
        a budgeted failure — fit() returns it in Result.error once the
        budget is gone, it does not raise out of the control loop."""
        from ray_tpu.train.controller import TrainController
        calls = {"n": 0}
        orig = TrainController._start_group

        def flaky(self, n=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("node died during gang formation")
            return orig(self, n)

        monkeypatch.setattr(TrainController, "_start_group", flaky)

        def ok_fn(config=None):
            import ray_tpu.train as train
            train.report({"step": 1})

        res = self._trainer(
            ok_fn, None,
            FailureConfig(max_failures=1, restart_backoff_initial_s=0.1),
            str(tmp_path)).fit()
        assert res.error is None, res.error
        assert res.num_failures == 1
        assert calls["n"] == 2


# -- watchdog drain suppression ---------------------------------------------


class TestWatchdogDrainSuppression:
    def test_draining_rank_never_trips_hang(self):
        from ray_tpu.train.watchdog import TrainWatchdog, WatchdogConfig
        wd = TrainWatchdog("run", WatchdogConfig(
            hang_deadline_s=0.3, poll_interval_s=0.05,
            capture_stacks=False, write_bundle=False))
        wd.start()
        try:
            wd.note_report(0, time.time(), pid=1,
                           report_mono=time.monotonic(), incarnation="a")
            wd.note_report(1, time.time(), pid=2,
                           report_mono=time.monotonic(), incarnation="b")
            # Rank 0's node is draining: its silence is planned.
            wd.note_drain([0], window_s=5.0)
            deadline = time.monotonic() + 2.5
            while time.monotonic() < deadline and wd.hang_count == 0:
                time.sleep(0.05)
            # Rank 1 (not draining) trips; rank 0 must not.
            assert wd.hang_count == 1
            assert wd.last_verdict["rank"] == 1
        finally:
            wd.stop()

    def test_draining_rank_never_flagged_straggler(self):
        from ray_tpu.train.watchdog import TrainWatchdog, WatchdogConfig
        wd = TrainWatchdog("run", WatchdogConfig(
            straggler_multiple=2.0, min_samples=2, capture_stacks=False,
            write_bundle=False, enabled=True))
        # Build baselines: two healthy ranks at ~0.1s intervals.
        t = 100.0
        for seq in range(4):
            for rank in (0, 1):
                wd.note_report(rank, time.time(), pid=rank,
                               report_mono=t, incarnation=f"i{rank}")
            t += 0.1
        wd.note_drain([0], window_s=30.0)
        before = wd.straggler_count
        # Rank 0 turns 20x slower — during its drain window.
        wd.note_report(0, time.time(), pid=0, report_mono=t + 2.0,
                       incarnation="i0")
        assert wd.straggler_count == before
        # An undrained rank with the same slowdown IS flagged.
        wd.note_report(1, time.time(), pid=1, report_mono=t + 2.0,
                       incarnation="i1")
        assert wd.straggler_count == before + 1


# -- train drain path: chaos SLA (tier-1, fast) -----------------------------


def _make_sla_train_fn():
    # Closure (not a module-level function): pickled by value, so node
    # SERVER workers — which cannot import the test module — can run it.
    def _sla_train_fn(config):
        import time as _t

        import numpy as np

        import ray_tpu.train as train
        from ray_tpu._private.api import _control

        ctx = train.get_context()
        world = ctx.get_world_size()

        def barrier(step):
            # Lockstep like a real SPMD step (collectives sync ranks):
            # without it ranks drift under load, and the all-rank commit
            # can only ever reach the SLOWEST rank's step — which would
            # make "lost work" measure drift, not recovery quality.
            prefix = f"tsync/{ctx.experiment_name}/{step}/"
            _control("kv_put", prefix + str(ctx.get_world_rank()), b"1")
            deadline = _t.monotonic() + 60
            while _t.monotonic() < deadline:
                if len(_control("kv_keys", prefix)) >= world:
                    return
                _t.sleep(0.02)

        state = train.load_checkpoint()
        start = 0 if state is None else int(state["step"])
        w = np.zeros((16,), np.float32) if state is None else state["w"]
        for step in range(start, config["steps"]):
            _t.sleep(config["step_time"])
            w = w + 1.0
            train.save_checkpoint({"w": w, "step": step + 1},
                                  metrics={"step": step + 1})
            train.report({"step": step + 1, "start": start})
            barrier(step)
    return _sla_train_fn


def _lost_steps(reports):
    from collections import Counter
    counts = Counter(r["metrics"]["step"] for r in reports
                     if r["rank"] == 0 and "step" in r["metrics"])
    return sum(c - 1 for c in counts.values() if c > 1)


def _run_with_chaos(cluster, victim, mode, steps, step_time,
                    write_delay, deadline_s, storage,
                    emergency_replica=False):
    """Drive one fit under a chaos schedule armed after real progress."""
    from ray_tpu.train.controller import TrainController
    env = dict(WORKER_ENV,
               RAY_TPU_CKPT_TEST_WRITE_DELAY_S=str(write_delay))
    trainer = JaxTrainer(
        _make_sla_train_fn(),
        train_loop_config={"steps": steps, "step_time": step_time},
        scaling_config=ScalingConfig(
            resources_per_worker={"CPU": 1}, min_workers=1,
            max_workers=2, elastic_check_interval_s=3600,
            env_per_worker=env),
        run_config=RunConfig(
            name=f"sla_{mode}", storage_path=storage,
            failure_config=FailureConfig(
                max_failures=1, restart_backoff_initial_s=0.2),
            checkpoint_config=CheckpointConfig(
                async_save=True, max_inflight=2,
                emergency_replica=emergency_replica)))
    controller = TrainController(trainer._train_fn, trainer._config,
                                 trainer._scaling, trainer._run_config)
    schedule = ChaosSchedule()
    if mode == "graceful":
        schedule.preempt(0.3, victim, deadline_s=deadline_s)
    else:
        schedule.kill(0.3, victim)
    runner = ChaosRunner(cluster, schedule, name=mode)
    box = {}

    def run():
        box["r"] = controller.run()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and t.is_alive():
        if any(r["metrics"].get("step", 0) >= 2
               for r in controller._reports):
            break
        time.sleep(0.1)
    runner.start()
    try:
        t.join(timeout=180)
        assert not t.is_alive(), f"{mode} run wedged"
    finally:
        runner.stop()
    return box["r"]


@pytest.fixture()
def chaos_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NODE_RECONNECT_GRACE_S", "0")
    c = Cluster(head_num_cpus=0)
    yield c
    c.shutdown()


class TestDrainSLA:
    def test_graceful_drain_beats_ungraceful_kill(self, chaos_cluster):
        """The acceptance SLA at smoke scale: identical preemption
        schedule, graceful (drain notice) vs ungraceful (SIGKILL).
        Graceful must complete with error=None at the reduced world
        size, burn zero failure budget, book the event as a drain, and
        lose <= 25% of the work the kill loses."""
        c = chaos_cluster
        c.add_node(num_cpus=1)
        knobs = dict(steps=14, step_time=0.25, write_delay=0.35,
                     deadline_s=8.0)

        n2 = c.add_node(num_cpus=1)
        store = tempfile.mkdtemp(prefix="sla_g_")
        res_g = _run_with_chaos(c, n2, "graceful", storage=store, **knobs)
        assert res_g.error is None, res_g.error
        assert res_g.metrics["step"] == knobs["steps"]
        assert res_g.num_drains == 1, res_g
        assert res_g.num_failures == 0  # no budget burned
        assert res_g.world_size_history[0] == 2
        assert res_g.world_size_history[-1] == 1  # reduced world
        lost_g = _lost_steps(res_g.all_reports)
        # Urgent flush committed every submitted save: ~0 lost work,
        # booked as restart (planned resize), not "lost".
        assert res_g.goodput["phases_s"].get("lost", 0.0) == \
            pytest.approx(0.0, abs=0.05)

        n3 = c.add_node(num_cpus=1)
        store = tempfile.mkdtemp(prefix="sla_u_")
        res_u = _run_with_chaos(c, n3, "ungraceful", storage=store,
                                **knobs)
        assert res_u.error is None, res_u.error
        assert res_u.metrics["step"] == knobs["steps"]
        assert res_u.num_failures == 1
        lost_u = _lost_steps(res_u.all_reports)
        # The slowed async writer guarantees in-flight (uncommitted)
        # saves at the kill: the crash path must lose real work...
        assert lost_u >= 1
        assert res_u.goodput["phases_s"].get("lost", 0.0) > 0.0
        # ...and the drain SLA holds with margin.
        assert lost_g <= 0.25 * lost_u

    def test_preemption_mid_async_save_flush_and_replica_restore(
            self, chaos_cluster):
        """Satellite chaos case: the notice fires while an async save is
        mid-write (slowed writer).  The urgent flush must commit it
        BEFORE the kill — every manifest on disk verifies, nothing is
        lost — and the downsized restart restores from peer RAM."""
        import ray_tpu.checkpoint as ck
        from ray_tpu.checkpoint import replica as rmod
        from ray_tpu._private import sanitizer
        from ray_tpu.util import metrics as mmod

        c = chaos_cluster
        n1 = c.add_node(num_cpus=1, resources={"pin": 1})
        n2 = c.add_node(num_cpus=1)
        # Pin the replica holder to the SURVIVING node before the
        # controller's ensure_holder runs (get_if_exists finds this one):
        # its RAM must outlive the preempted node for the
        # restore-from-RAM assertion to be deterministic.
        sanitizer.session_scoped(rmod.holder_name("*"))
        holder_cls = ray_tpu.remote(rmod.ReplicaHolder)
        holder = holder_cls.options(name=rmod.holder_name("sla_graceful"),
                                    get_if_exists=True, num_cpus=0,
                                    resources={"pin": 0.001}).remote()
        ray_tpu.get(holder.stats.remote(), timeout=60)  # placed + live

        def replica_restores():
            for line in mmod.prometheus_text().splitlines():
                if line.startswith("ray_tpu_ckpt_replica_restores_total"):
                    return float(line.split()[-1])
            return 0.0

        before = replica_restores()
        store = tempfile.mkdtemp(prefix="sla_mid_")
        res = _run_with_chaos(
            c, n2, "graceful", steps=12, step_time=0.2,
            write_delay=0.4, deadline_s=8.0, storage=store,
            emergency_replica=True)
        assert res.error is None, res.error
        assert res.num_drains == 1, res
        assert res.metrics["step"] == 12
        # Zero re-executed steps: the mid-write save committed under the
        # urgent flush before the node died.
        assert _lost_steps(res.all_reports) == 0
        # Every directory claiming to be a checkpoint verifies deeply.
        import os
        run_dir = os.path.join(store, "sla_graceful")
        committed = [r for r in ck.scan_run_dir(run_dir, deep=True)
                     if r["committed"]]
        assert committed
        for rec in committed:
            assert rec["valid"], rec
        # The post-drain incarnation restored from the peer-RAM replica.
        assert replica_restores() > before, \
            "restore after drain did not prefer peer RAM"
        assert n1.alive


# -- serve replica evacuation ----------------------------------------------


class TestServeDrainEvacuation:
    def test_replicas_move_off_draining_node(self, chaos_cluster):
        """Drain a node hosting a serve replica: the controller must
        unpublish + replace it proactively (reusing the settle-kill
        drain path) on a non-draining node — no crash, no gap at the
        target replica count."""
        from ray_tpu import serve

        c = chaos_cluster
        nodes = [c.add_node(num_cpus=1) for _ in range(3)]

        @serve.deployment(name="echo", num_replicas=2, num_cpus=1)
        class Echo:
            def __call__(self, x):
                return x

        serve.run(Echo.bind(), name="echo")
        try:
            handle = serve.get_deployment_handle("echo")
            assert ray_tpu.get(handle.remote("hi"), timeout=30) == "hi"

            def replica_nodes():
                acts = _control("list_actors",
                                {"class_name": "_ReplicaActor",
                                 "state": "ALIVE"})
                return {a["actor_id"]: a["node_id"] for a in acts}

            # Both replicas ALIVE on distinct nodes (1-CPU nodes force a
            # spread).  Poll: a replica can still be binding/restarting
            # in the instant after serve.run returns under suite load.
            deadline = time.monotonic() + 30
            occupied: set = set()
            while time.monotonic() < deadline:
                occupied = set(replica_nodes().values())
                if len(occupied) == 2:
                    break
                time.sleep(0.2)
            assert len(occupied) == 2, replica_nodes()
            victim_hex = next(iter(occupied))
            victim = next(n for n in nodes if n.node_id == victim_hex)
            assert _control("drain_node", victim.node_id, 30.0,
                            "preemption") is True

            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                placed = replica_nodes()
                live_elsewhere = [a for a, n in placed.items()
                                  if n != victim_hex]
                if len(live_elsewhere) >= 2:
                    break
                time.sleep(0.2)
            placed = replica_nodes()
            assert len([a for a, n in placed.items()
                        if n != victim_hex]) >= 2, placed
            # Still serving through the whole evacuation.
            assert ray_tpu.get(handle.remote("again"), timeout=30) \
                == "again"
        finally:
            serve.shutdown()


# -- instance manager: provider notices -> drain hook ------------------------


class TestProviderPreemptionNotices:
    def _manager(self, provider, hook):
        from ray_tpu.autoscaler.instance_manager import InstanceManager
        return InstanceManager(provider, joined_pids=lambda: {},
                               drain_hook=hook)

    def test_notice_for_joined_instance_fires_drain_hook_once(self):
        from ray_tpu.autoscaler.instance_manager import (FakeCloudProvider,
                                                         JOINED)
        provider = FakeCloudProvider()
        calls = []
        mgr = self._manager(provider,
                            lambda nid, d, r: calls.append((nid, d, r)))
        mgr.reconcile({"tpu": 1})
        mgr.reconcile({"tpu": 1})
        inst = mgr.store.alive()[0]
        inst.ray_node_id = "node-abc"
        mgr.store.upsert(inst, JOINED)

        provider.preempt_notice(inst.cloud_id, deadline_s=25.0)
        mgr.reconcile({"tpu": 1})
        mgr.reconcile({"tpu": 1})  # notices repeat; the drain must not
        assert calls == [("node-abc", 25.0, "preemption")]

    def test_notice_during_boot_window_fires_after_join(self):
        """A reclaim warning landing while the instance is RUNNING (not
        yet JOINED) must not be swallowed: the hook retries until the
        node joins, then drains it — the graceful path survives the
        boot->join race."""
        from ray_tpu.autoscaler.instance_manager import (FakeCloudProvider,
                                                         JOINED, RUNNING)
        provider = FakeCloudProvider()
        calls = []
        mgr = self._manager(provider,
                            lambda nid, d, r: calls.append((nid, d, r)))
        mgr.reconcile({"tpu": 1})
        mgr.reconcile({"tpu": 1})
        inst = mgr.store.alive()[0]
        assert inst.status == RUNNING  # booted, not joined
        provider.preempt_notice(inst.cloud_id, deadline_s=30.0)
        mgr.reconcile({"tpu": 1})
        assert calls == []  # no join yet: nothing to drain
        inst.ray_node_id = "node-late"
        mgr.store.upsert(inst, JOINED)
        mgr.reconcile({"tpu": 1})
        mgr.reconcile({"tpu": 1})
        assert calls == [("node-late", 30.0, "preemption")]

    def test_cloud_lost_instance_counts_preempted(self):
        from ray_tpu.autoscaler import instance_manager as im
        from ray_tpu.autoscaler.instance_manager import (FakeCloudProvider,
                                                         JOINED,
                                                         TERMINATED)
        from ray_tpu.util import metrics as mmod

        def preempted_total():
            for line in mmod.prometheus_text().splitlines():
                if line.startswith("ray_tpu_node_preempted_total"):
                    return float(line.split()[-1])
            return 0.0

        provider = FakeCloudProvider()
        events = []
        mgr = self._manager(provider, lambda *a: None)
        old_export = im._export_node_event
        im._export_node_event = events.append
        try:
            mgr.reconcile({"tpu": 1})
            mgr.reconcile({"tpu": 1})  # second pass binds the cloud_id
            inst = mgr.store.alive()[0]
            assert inst.cloud_id
            inst.ray_node_id = "node-xyz"
            mgr.store.upsert(inst, JOINED)
            before = preempted_total()
            provider.lose_instance(inst.cloud_id)
            mgr.reconcile({"tpu": 1})
            assert inst.status == TERMINATED
            assert preempted_total() == before + 1
            preempt_events = [e for e in events
                              if e.get("state") == "PREEMPTED"]
            assert len(preempt_events) == 1
            assert preempt_events[0]["node_id"] == "node-xyz"
        finally:
            im._export_node_event = old_export

    def test_own_terminate_is_not_a_preemption(self):
        from ray_tpu.autoscaler.instance_manager import (FakeCloudProvider,
                                                         RUNNING)
        from ray_tpu.util import metrics as mmod

        def preempted_total():
            for line in mmod.prometheus_text().splitlines():
                if line.startswith("ray_tpu_node_preempted_total"):
                    return float(line.split()[-1])
            return 0.0

        provider = FakeCloudProvider()
        mgr = self._manager(provider, lambda *a: None)
        mgr.reconcile({"tpu": 1})
        while not any(i.status == RUNNING for i in mgr.store.alive()):
            mgr.reconcile({"tpu": 1})
        before = preempted_total()
        mgr.reconcile({"tpu": 0})  # scale to zero: WE terminate it
        for _ in range(3):
            mgr.reconcile({"tpu": 0})
        assert preempted_total() == before


# -- worker-death bundle tagging --------------------------------------------


class TestPreemptedDeathBundleTag:
    def test_death_on_draining_node_tagged_preempted(
            self, ray_start_isolated):
        """A worker dying on a draining node is the EXPECTED half of a
        preemption: the flight-recorder bundle must say so."""
        import glob
        import json
        import os

        @ray_tpu.remote
        def die_on_signal():
            import os as _os
            import time as _t

            from ray_tpu._private.api import _control as _c
            while _c("kv_get", "chaos/die") is None:
                _t.sleep(0.05)
            _os._exit(1)

        rt = ray_start_isolated
        hexid = _control("nodes")[0]["node_id"]
        # Start the task FIRST (a draining node takes no new leases),
        # then drain, then pull the trigger.
        ref = die_on_signal.remote()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(t.get("state") == "RUNNING"
                   for t in _control("list_tasks",
                                     {"name": "die_on_signal"})):
                break
            time.sleep(0.1)
        assert _control("drain_node", hexid, 30.0, "spot-reclaim")
        _control("kv_put", "chaos/die", b"1")
        try:
            with pytest.raises(Exception):
                ray_tpu.get(ref, timeout=60)
        finally:
            _control("kv_del", "chaos/die")
        session = _control("session_dir")
        deadline = time.monotonic() + 15
        bundles = []
        while time.monotonic() < deadline and not bundles:
            bundles = glob.glob(os.path.join(
                session, "debug", "*worker_death_preempted*"))
            time.sleep(0.2)
        assert bundles, "no preempted-tagged death bundle written"
        with open(os.path.join(bundles[0], "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["extra"]["reason"] == "preempted"
        assert manifest["extra"]["node_draining"] is True
        assert rt is not None
