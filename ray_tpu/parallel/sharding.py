"""Logical-axis sharding rules (the Megatron/t5x-style rule table).

Model code annotates arrays with *logical* dimension names ("batch", "seq",
"embed", "mlp", "heads", "vocab", "expert", "layers"); a ``ShardingRules``
table maps each logical name to zero or more mesh axes.  Changing the
parallelism strategy = changing the table, not the model.  XLA then inserts
the allreduce/allgather/reducescatter collectives implied by the placements
(scaling-book recipe; no NCCL-style explicit communication as in the
reference's DDP path, reference: python/ray/train/torch/config.py:95).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple, Union

from .mesh import (AXIS_DATA, AXIS_EXPERT, AXIS_FSDP, AXIS_PIPELINE,
                   AXIS_SEQ, AXIS_TENSOR)

MeshAxes = Union[None, str, Tuple[str, ...]]


@dataclass
class ShardingRules:
    rules: Dict[str, MeshAxes] = field(default_factory=dict)

    def axes_for(self, logical: str) -> MeshAxes:
        return self.rules.get(logical)

    def replace(self, **updates: MeshAxes) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(updates)
        return ShardingRules(merged)


def default_rules() -> ShardingRules:
    """FSDP+TP+SP+EP layout for transformer LMs.

    - batch over (dp, fsdp): every data shard trains a distinct slice
    - embed dim sharded over tp for attention/MLP projections (Megatron)
    - the *other* matmul dim of each weight sharded over fsdp (ZeRO-3-style
      parameter sharding; XLA all-gathers just-in-time per layer)
    - sequence over sp (ring/Ulysses context parallelism in ops/)
    - experts over ep
    """
    return ShardingRules({
        "batch": (AXIS_DATA, AXIS_FSDP),
        "seq": AXIS_SEQ,
        "embed": AXIS_FSDP,
        "heads": AXIS_TENSOR,
        "kv_heads": AXIS_TENSOR,
        "head_dim": None,
        "mlp": AXIS_TENSOR,
        "vocab": AXIS_TENSOR,
        "expert": AXIS_EXPERT,
        "layers": None,
        "stage": AXIS_PIPELINE,
        "norm": None,
    })


def logical_to_pspec(logical_axes: Sequence[Optional[str]],
                     rules: ShardingRules):
    """('batch','seq','embed') -> PartitionSpec((dp,fsdp), sp, fsdp)."""
    from jax.sharding import PartitionSpec
    entries = []
    used: set = set()
    for name in logical_axes:
        axes = rules.axes_for(name) if name is not None else None
        if axes is None:
            entries.append(None)
            continue
        axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        # A mesh axis may shard at most one dim of a given array.
        axes_t = tuple(a for a in axes_t if a not in used)
        used.update(axes_t)
        if not axes_t:
            entries.append(None)
        elif len(axes_t) == 1:
            entries.append(axes_t[0])
        else:
            entries.append(axes_t)
    return PartitionSpec(*entries)


def named_sharding(mesh, logical_axes: Sequence[Optional[str]],
                   rules: Optional[ShardingRules] = None):
    from jax.sharding import NamedSharding
    rules = rules or default_rules()
    return NamedSharding(mesh, logical_to_pspec(logical_axes, rules))


def shard_pytree(tree, logical_tree, mesh,
                 rules: Optional[ShardingRules] = None):
    """Device_put a pytree according to a parallel pytree of logical axes."""
    import jax
    rules = rules or default_rules()

    def place(x, logical):
        return jax.device_put(x, named_sharding(mesh, logical, rules))
    return jax.tree.map(place, tree, logical_tree,
                        is_leaf=lambda x: x is None)


def pspec_pytree(logical_tree, rules: Optional[ShardingRules] = None):
    """Parallel pytree of PartitionSpecs from a pytree of logical axes."""
    import jax
    rules = rules or default_rules()
    return jax.tree.map(
        lambda logical: logical_to_pspec(logical, rules), logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list)) and all(
            isinstance(e, (str, type(None))) for e in x))


def constrain(x, logical_axes: Sequence[Optional[str]],
              rules: Optional[ShardingRules] = None):
    """with_sharding_constraint by logical names (inside jit)."""
    import jax
    rules = rules or default_rules()
    return jax.lax.with_sharding_constraint(
        x, logical_to_pspec(logical_axes, rules))
