"""Offline RL: dataset IO + BC / MARWIL / discrete CQL.

Reference: rllib/offline/ (offline_data.py — datasets read through Ray
Data and streamed to learners) and rllib/algorithms/{bc,marwil,cql}/.
Episode data is stored as columnar parquet shards written and read
through ``ray_tpu.data`` (distributed read tasks, streaming executor),
so offline preprocessing composes with the Data pipeline ops
(map_batches, shuffle, repartition); .npz shards remain supported as the
zero-dependency local format.

Algorithms:
  * BC      — behavior cloning: max log pi(a|s) (discrete cross-entropy /
              continuous Gaussian log-prob).
  * MARWIL  — advantage-weighted BC: exp(beta * A) weights with a learned
              value baseline (reference: rllib/algorithms/marwil).
  * CQL     — conservative Q-learning (discrete): DQN TD loss +
              alpha * (logsumexp Q - Q(a_data)) penalty pushing down
              out-of-distribution action values (reference:
              rllib/algorithms/cql, discrete form).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .learner import JaxLearner
from .rl_module import (ContinuousModuleSpec, DiscretePolicyModule,
                        GaussianPolicyModule, QModule)

REQUIRED_COLUMNS = ("obs", "actions")


def save_shard(path: str, columns: Dict[str, np.ndarray]) -> str:
    """Write one columnar shard: a ``.npz`` file, or (any other path) a
    directory of parquet shards written through ray_tpu.data."""
    for c in REQUIRED_COLUMNS:
        if c not in columns:
            raise ValueError(f"offline shard missing column {c!r}")
    if not path.endswith(".npz"):
        return save_parquet(path, columns)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(path, **columns)
    return path


def save_parquet(path: str, columns: Dict[str, np.ndarray],
                 shards: int = 4) -> str:
    """Write episode columns as parquet shards via the Data pipeline
    (reference: rllib offline writers emitting parquet through Ray Data).
    Vector columns (obs) become per-dimension scalar columns
    ``name/<i>``; readers stack them back."""
    from ray_tpu import data as rdata
    out: Dict[str, np.ndarray] = {}
    n = len(next(iter(columns.values())))
    for k, v in columns.items():
        v = np.asarray(v)
        if v.ndim == 1:
            out[k] = v
        elif v.ndim == 2:
            for i in range(v.shape[1]):
                out[f"{k}/{i}"] = v[:, i]
        else:
            raise ValueError(
                f"parquet episode column {k!r} has ndim={v.ndim}; flatten "
                "to <= 2 dims first")
        assert len(v) == n, f"column {k!r} length mismatch"
    ds = rdata.Dataset.from_numpy(out, parallelism=shards)
    ds.write_parquet(path)
    return path


def _unflatten_columns(cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Inverse of save_parquet's vector flattening: stack name/<i>."""
    out: Dict[str, np.ndarray] = {}
    grouped: Dict[str, Dict[int, np.ndarray]] = {}
    for k, v in cols.items():
        if "/" in k:
            base, _, idx = k.rpartition("/")
            try:
                grouped.setdefault(base, {})[int(idx)] = v
                continue
            except ValueError:
                pass
        out[k] = v
    for base, parts in grouped.items():
        out[base] = np.stack([parts[i] for i in range(len(parts))], axis=1)
    return out


def collect_from_env(env_spec: Any, policy_fn, num_steps: int,
                     path: str, *, seed: int = 0,
                     gamma: float = 0.99) -> str:
    """Roll a behavior policy in an env and save the transitions (with
    per-step discounted returns-to-go for MARWIL/CQL targets)."""
    env = make_env(env_spec)
    rng = np.random.default_rng(seed)
    obs, _ = env.reset(seed=seed)
    cols: Dict[str, List] = {k: [] for k in
                             ("obs", "actions", "rewards", "next_obs",
                              "terminateds")}
    ep_start = 0
    returns: List[float] = []
    for t in range(num_steps):
        action = policy_fn(obs, rng)
        next_obs, r, term, trunc, _ = env.step(action)
        cols["obs"].append(obs)
        cols["actions"].append(action)
        cols["rewards"].append(r)
        cols["next_obs"].append(next_obs)
        cols["terminateds"].append(float(term))
        obs = next_obs
        if term or trunc:
            obs, _ = env.reset()
            # Fill discounted returns-to-go for the finished episode.
            ep_rewards = cols["rewards"][ep_start:]
            g = 0.0
            rtg = []
            for rr in reversed(ep_rewards):
                g = rr + gamma * g
                rtg.append(g)
            returns.extend(reversed(rtg))
            ep_start = len(cols["rewards"])
    # Trailing partial episode: bootstrap-free returns-to-go.
    ep_rewards = cols["rewards"][ep_start:]
    g = 0.0
    rtg = []
    for rr in reversed(ep_rewards):
        g = rr + gamma * g
        rtg.append(g)
    returns.extend(reversed(rtg))
    out = {
        "obs": np.asarray(cols["obs"], np.float32),
        "actions": np.asarray(cols["actions"]),
        "rewards": np.asarray(cols["rewards"], np.float32),
        "next_obs": np.asarray(cols["next_obs"], np.float32),
        "terminateds": np.asarray(cols["terminateds"], np.float32),
        "returns_to_go": np.asarray(returns, np.float32),
    }
    return save_shard(path, out)


class OfflineData:
    """Columnar dataset over .npz shards or parquet directories
    (reference: rllib/offline/offline_data.py — parquet episode data read
    through the Data library).

    Parquet paths (a directory from ``save_parquet`` / ``write_parquet``,
    a ``*.parquet`` glob, or a ``ray_tpu.data.Dataset``) stream through
    the Data executor: shard reads run as tasks and batches flow back
    through ``iter_batches`` — the npz path stays a zero-runtime local
    loader."""

    def __init__(self, paths, seed: int = 0):
        from ray_tpu.data import Dataset as _DataDataset
        if isinstance(paths, _DataDataset):
            self.columns = self._from_dataset(paths)
        else:
            if isinstance(paths, str):
                expanded = sorted(glob.glob(paths)) if any(
                    ch in paths for ch in "*?[") else [paths]
            else:
                expanded = list(paths)
            if not expanded:
                raise ValueError("no offline data shards found")
            if all(p.endswith(".npz") for p in expanded):
                parts: Dict[str, List[np.ndarray]] = {}
                for p in expanded:
                    with np.load(p) as z:
                        for k in z.files:
                            parts.setdefault(k, []).append(z[k])
                self.columns = {k: np.concatenate(v)
                                for k, v in parts.items()}
            else:
                from ray_tpu import data as rdata
                files: List[str] = []
                for p in expanded:
                    files.extend(sorted(
                        glob.glob(os.path.join(p, "*.parquet")))
                        if os.path.isdir(p) else [p])
                if not files:
                    raise ValueError(f"no parquet shards under {paths!r}")
                self.columns = self._from_dataset(
                    rdata.read_parquet(files))
        self.size = len(self.columns["obs"])
        self._rng = np.random.default_rng(seed)

    @staticmethod
    def _from_dataset(ds) -> Dict[str, np.ndarray]:
        parts: Dict[str, List[np.ndarray]] = {}
        # Streaming consumption: shard reads execute as Data tasks while
        # earlier batches are already being accumulated here.
        for batch in ds.iter_batches(batch_size=4096):
            for k, v in batch.items():
                parts.setdefault(k, []).append(np.asarray(v))
        if not parts:
            raise ValueError("offline dataset is empty")
        return _unflatten_columns(
            {k: np.concatenate(v) for k, v in parts.items()})

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.size, batch_size)
        return {k: c[idx] for k, c in self.columns.items()}


# ------------------------------------------------------------------------- #
# BC
# ------------------------------------------------------------------------- #

def bc_discrete_loss(module: DiscretePolicyModule, params, batch):
    import jax
    import jax.numpy as jnp
    out = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    w = batch.get("bc_weights")
    loss = -jnp.mean(w * logp) if w is not None else -jnp.mean(logp)
    return loss, {"logp_mean": jnp.mean(logp)}


def bc_continuous_loss(module: GaussianPolicyModule, params, batch):
    import jax.numpy as jnp
    # Maximize the squashed-Gaussian log-prob of dataset actions by
    # matching the pre-squash mean (stable, standard practice for
    # tanh policies): MSE on the inverse-squashed action + std penalty.
    mean, log_std = module._dist(params, batch["obs"])
    scale, mid = module._scale, module._mid
    squashed = jnp.clip((batch["actions"] - mid) / scale, -0.999, 0.999)
    pre_tanh = jnp.arctanh(squashed)
    mse = jnp.mean(jnp.sum((mean - pre_tanh) ** 2, axis=-1))
    std_pen = jnp.mean(jnp.sum(log_std ** 2, axis=-1))
    return mse + 1e-3 * std_pen, {"bc_mse": mse}


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BC)
        self.input_path: Optional[str] = None
        self.train_batch_size = 256
        self.updates_per_iteration = 50

    def offline_data(self, *, input_path: str,
                     updates_per_iteration: Optional[int] = None
                     ) -> "BCConfig":
        self.input_path = input_path
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self


class BC(Algorithm):
    """Behavior cloning from offline shards (reference:
    rllib/algorithms/bc)."""

    _use_env_runner_group = False
    _loss_fns = (bc_discrete_loss, bc_continuous_loss)

    def setup(self, config: BCConfig) -> None:
        if config.input_path is None:
            raise ValueError("BCConfig.offline_data(input_path=...) required")
        self.data = OfflineData(config.input_path, seed=config.seed)
        env = make_env(config.env_spec)
        self.env = env
        if env.is_continuous:
            spec = ContinuousModuleSpec(
                env.observation_dim, env.action_dim, env.action_low,
                env.action_high, tuple(config.module_hidden))
            self.module = GaussianPolicyModule(spec)
            loss = type(self)._loss_fns[1]
        else:
            self.module = DiscretePolicyModule(config.module_spec())
            loss = type(self)._loss_fns[0]
        self.learner = JaxLearner(self.module, self._wrap_loss(loss),
                                  learning_rate=config.lr, seed=config.seed)
        import jax
        self._infer = jax.jit(self.module.forward_inference)

    def _wrap_loss(self, loss):
        return loss

    def training_step(self) -> Dict[str, Any]:
        cfg: BCConfig = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            batch = self.data.sample(cfg.train_batch_size)
            metrics = self.learner.update(self._prepare_batch(batch))
        return {"learner": metrics, "dataset_size": self.data.size}

    def _prepare_batch(self, batch: Dict[str, np.ndarray]):
        return {"obs": batch["obs"], "actions": batch["actions"]}

    def compute_single_action(self, obs: np.ndarray):
        out = self._infer(self.learner.params, obs[None])
        a = np.asarray(out)[0]
        return a if self.env.is_continuous else int(a)

    def get_weights(self):
        return self.learner.params

    def set_weights(self, params) -> None:
        self.learner.set_weights(params)


# ------------------------------------------------------------------------- #
# MARWIL (discrete)
# ------------------------------------------------------------------------- #

def marwil_loss(module: DiscretePolicyModule, params, batch):
    import jax
    import jax.numpy as jnp
    out = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    adv = batch["returns_to_go"] - out["value"]
    vf_loss = jnp.mean(adv ** 2)
    beta = batch["beta"][0]
    # exp-advantage weights, gradient-stopped and clipped for stability
    # (reference: marwil.py's c^2 normalization, simplified).
    w = jnp.clip(jnp.exp(beta * jax.lax.stop_gradient(
        adv / (jnp.std(jax.lax.stop_gradient(adv)) + 1e-6))), 0.0, 20.0)
    pi_loss = -jnp.mean(w * logp)
    return pi_loss + 0.5 * vf_loss, {
        "pi_loss": pi_loss, "vf_loss": vf_loss, "w_mean": jnp.mean(w)}


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0

    def training(self, *, beta=None, **kw) -> "MARWILConfig":
        super().training(**kw)
        if beta is not None:
            self.beta = beta
        return self


class MARWIL(BC):
    """Advantage-weighted behavior cloning (reference:
    rllib/algorithms/marwil — beta=0 degenerates to BC)."""

    _loss_fns = (marwil_loss, bc_continuous_loss)

    def setup(self, config: MARWILConfig) -> None:
        super().setup(config)
        if self.env.is_continuous:
            raise ValueError("MARWIL here supports discrete envs; "
                             "use BC/SAC for continuous")
        if "returns_to_go" not in self.data.columns:
            raise ValueError("MARWIL needs returns_to_go in the dataset "
                             "(collect_from_env writes it)")

    def _prepare_batch(self, batch):
        return {"obs": batch["obs"], "actions": batch["actions"],
                "returns_to_go": batch["returns_to_go"],
                "beta": np.array([self.config.beta], np.float32)}


# ------------------------------------------------------------------------- #
# CQL (discrete)
# ------------------------------------------------------------------------- #

def cql_loss(module: QModule, params, batch):
    import jax.numpy as jnp
    from jax.scipy.special import logsumexp
    q = module.q_values(params, batch["obs"])
    q_taken = jnp.take_along_axis(
        q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    td = jnp.mean((q_taken - batch["targets"]) ** 2)
    # Conservative penalty: soft-max over all actions minus the data action
    # — pushes down Q for actions the behavior policy never took.
    cql = jnp.mean(logsumexp(q, axis=-1) - q_taken)
    alpha = batch["cql_alpha"][0]
    return td + alpha * cql, {"td_loss": td, "cql_penalty": cql,
                              "q_mean": jnp.mean(q_taken)}


class CQLConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.cql_alpha = 1.0
        self.target_update_freq = 10  # in updates

    def training(self, *, cql_alpha=None, target_update_freq=None,
                 **kw) -> "CQLConfig":
        super().training(**kw)
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        if target_update_freq is not None:
            self.target_update_freq = target_update_freq
        return self


class CQL(Algorithm):
    """Discrete conservative Q-learning over offline transitions
    (reference: rllib/algorithms/cql; discrete-action form)."""

    _use_env_runner_group = False

    def setup(self, config: CQLConfig) -> None:
        import jax
        if config.input_path is None:
            raise ValueError("CQLConfig.offline_data(input_path=...) "
                             "required")
        self.data = OfflineData(config.input_path, seed=config.seed)
        for c in ("rewards", "next_obs", "terminateds"):
            if c not in self.data.columns:
                raise ValueError(f"CQL needs transition column {c!r}")
        self.env = make_env(config.env_spec)
        self.module = QModule(config.module_spec())
        self.learner = JaxLearner(self.module, cql_loss,
                                  learning_rate=config.lr, seed=config.seed)
        self.target_params = self.learner.params
        self._q_fn = jax.jit(self.module.q_values)

        def targets_dev(target_params, next_obs, rewards, terminateds):
            # Bellman target on device: the old path shipped the whole
            # [B, A] q-table to host per update just to max over it.
            import jax.numpy as jnp
            q_next = self.module.q_values(target_params, next_obs)
            return (rewards + config.gamma * (1.0 - terminateds)
                    * q_next.max(-1)).astype(jnp.float32)

        self._targets_fn = jax.jit(targets_dev)
        self._n_updates = 0

    def training_step(self) -> Dict[str, Any]:
        import jax
        cfg: CQLConfig = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            batch = self.data.sample(cfg.train_batch_size)
            targets = jax.device_get(self._targets_fn(
                self.target_params, batch["next_obs"], batch["rewards"],
                batch["terminateds"]))
            metrics = self.learner.update({
                "obs": batch["obs"], "actions": batch["actions"],
                "targets": targets,
                "cql_alpha": np.array([cfg.cql_alpha], np.float32)})
            self._n_updates += 1
            if self._n_updates % cfg.target_update_freq == 0:
                self.target_params = self.learner.params
        return {"learner": metrics, "dataset_size": self.data.size}

    def compute_single_action(self, obs: np.ndarray) -> int:
        q = np.asarray(self._q_fn(self.learner.params, obs[None]))[0]
        return int(np.argmax(q))

    def get_weights(self):
        return self.learner.params

    def set_weights(self, params) -> None:
        self.learner.set_weights(params)
        self.target_params = params
