"""Distributed checkpointing subsystem: wire-format roundtrips, the
resharding restore matrix, manifest commit atomicity (coordinator-crash
chaos), async-writer semantics, retention/to_directory atomicity, the
``ray-tpu ckpt`` CLI, and JaxTrainer e2e (kill-mid-async-save chaos,
emergency-replica restore)."""

from __future__ import annotations

import json
import os
import pickle

import numpy as np
import pytest

import ray_tpu.checkpoint as ck
from ray_tpu.checkpoint.manager import CheckpointManager, step_dir


def _tree():
    return {
        "params": {
            "dense": {"kernel": np.arange(32, dtype=np.float32)
                      .reshape(8, 4),
                      "bias": np.ones(4, np.float64)},
            "emb": np.arange(12, dtype=np.int32).reshape(3, 4),
        },
        "step": 7,
        "opt": [np.zeros(5, np.float32), {"count": 3}],
        "name": "run-a",
        "none_node": None,
    }


def _tree_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if hasattr(x, "shape"):
            assert np.array_equal(np.asarray(x), np.asarray(y))
            assert np.asarray(x).dtype == np.asarray(y).dtype
        else:
            assert x == y


def _save_world(root, step, world, tree_per_rank, shard_spec_per_rank=None):
    """Write every rank's shards + commit the manifest (the coordinator
    steps run inline — this is the format-level harness)."""
    d = step_dir(root, step)
    for rank in range(world):
        spec = shard_spec_per_rank(rank) if shard_spec_per_rank else None
        snap = ck.snapshot_tree(tree_per_rank(rank), shard_spec=spec)
        index, blob = ck.build_shard(snap, rank, world, step)
        ck.write_shard(d, index, blob,
                       skeleton_pkl=snap.skeleton_pkl if rank == 0
                       else None)
    manifest = ck.build_manifest(d, step, world)
    ck.commit_manifest(d, manifest)
    return d


class TestFormatRoundtrip:
    def test_world1_mixed_tree_bit_exact(self, tmp_path):
        tree = _tree()
        d = _save_world(str(tmp_path), 0, 1, lambda r: tree)
        assert ck.verify_checkpoint(d, deep=True) == []
        _tree_equal(ck.restore_tree(d), tree)

    def test_legacy_pickle_layout_still_loads(self, tmp_path):
        tree = _tree()
        d = str(tmp_path / "legacy")
        os.makedirs(d)
        ck.save_pytree(tree, d)
        assert not ck.is_committed(d)
        _tree_equal(ck.load_pytree(d), tree)
        # Checkpoint handle auto-detects the layout.
        from ray_tpu.train import Checkpoint
        _tree_equal(Checkpoint(d).load_pytree(), tree)

    def test_load_pytree_detects_sharded_layout(self, tmp_path):
        tree = _tree()
        d = _save_world(str(tmp_path), 3, 1, lambda r: tree)
        _tree_equal(ck.load_pytree(d), tree)


class TestReshardingMatrix:
    """Save at world W, restore at world W' — pytree equality across
    {1->2, 2->1, 2->4} (the acceptance matrix) plus a partial-overlap
    gather case."""

    GLOBAL = np.arange(64, dtype=np.float32).reshape(8, 8)

    def _rank_tree(self, world):
        def make(rank):
            idx = ck.even_shard(self.GLOBAL.shape, 0, rank, world)
            (r0, r1), _ = idx
            return {"w": self.GLOBAL[r0:r1], "bias": np.ones(3),
                    "step": 5}
        return make

    def _spec(self, world):
        def for_rank(rank):
            def spec(key, leaf):
                if key == "w":
                    return (self.GLOBAL.shape,
                            ck.even_shard(self.GLOBAL.shape, 0, rank,
                                          world))
                return tuple(leaf.shape), ck.full_index(leaf.shape)
            return spec
        return for_rank

    @pytest.mark.parametrize("save_world,restore_world",
                             [(1, 2), (2, 1), (2, 4)])
    def test_matrix(self, tmp_path, save_world, restore_world):
        d = _save_world(str(tmp_path), 0, save_world,
                        self._rank_tree(save_world),
                        self._spec(save_world))
        assert ck.verify_checkpoint(d, deep=True) == []
        # Each restore rank fetches exactly its slice...
        parts = []
        for rank in range(restore_world):
            out = ck.restore_tree(
                d, placement=ck.even_placement(0, rank, restore_world))
            idx = ck.even_shard(self.GLOBAL.shape, 0, rank, restore_world)
            (r0, r1), _ = idx
            assert np.array_equal(out["w"], self.GLOBAL[r0:r1])
            assert out["step"] == 5
            parts.append(out["w"])
        # ...and the parts reassemble the global array bit-exact.
        assert np.array_equal(np.concatenate(parts, axis=0), self.GLOBAL)

    def test_partial_overlap_gather(self, tmp_path):
        # Save split 3 ways (uneven), restore split 2 ways: every target
        # block straddles stored-chunk boundaries -> generic gather.
        d = _save_world(str(tmp_path), 0, 3, self._rank_tree(3),
                        self._spec(3))
        for rank in range(2):
            out = ck.restore_tree(
                d, placement=ck.even_placement(0, rank, 2))
            (r0, r1), _ = ck.even_shard(self.GLOBAL.shape, 0, rank, 2)
            assert np.array_equal(out["w"], self.GLOBAL[r0:r1])

    def test_missing_coverage_is_loud(self, tmp_path):
        # Only rank 1's half saved at world 2 but the manifest claims
        # world 1... simulate by saving a single rank owning rows 4:8 and
        # asking for the full array.
        def spec(key, leaf):
            if key == "w":
                return ((8, 8), ((4, 8), (0, 8)))
            return tuple(leaf.shape), ck.full_index(leaf.shape)
        d = _save_world(str(tmp_path), 0, 1,
                        lambda r: {"w": self.GLOBAL[4:8], "bias":
                                   np.ones(3), "step": 5},
                        lambda rank: spec)
        with pytest.raises(ck.CheckpointError, match="cover"):
            ck.restore_tree(d)


class TestManifestAtomicity:
    def test_uncommitted_dir_is_not_a_checkpoint(self, tmp_path):
        tree = _tree()
        d = step_dir(str(tmp_path), 0)
        snap = ck.snapshot_tree(tree)
        index, blob = ck.build_shard(snap, 0, 1, 0)
        ck.write_shard(d, index, blob, skeleton_pkl=snap.skeleton_pkl)
        # All data present, no manifest: invalid by definition.
        assert not ck.is_committed(d)
        assert ck.verify_checkpoint(d) == [
            "no manifest (uncommitted or not a checkpoint)"]

    def test_torn_manifest_fails_checksum(self, tmp_path):
        d = _save_world(str(tmp_path), 0, 1, lambda r: _tree())
        mpath = os.path.join(d, "manifest.json")
        raw = open(mpath, "rb").read()
        # A torn tail that still parses as JSON must NOT validate: flip
        # a recorded size instead of truncating.
        doc = json.loads(raw)
        doc["total_bytes"] += 1
        with open(mpath + ".tmp", "wb") as f:
            f.write(json.dumps(doc).encode())
        os.replace(mpath + ".tmp", mpath)
        problems = ck.verify_checkpoint(d)
        assert problems and "checksum" in problems[0]
        with pytest.raises(ck.CheckpointError, match="checksum"):
            ck.restore_tree(d)

    def test_bit_rot_caught_by_deep_verify(self, tmp_path):
        d = _save_world(str(tmp_path), 0, 1, lambda r: _tree())
        [data_file] = [f for f in os.listdir(d) if f.endswith(".bin")]
        p = os.path.join(d, data_file)
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(p + ".tmp", "wb") as f:
            f.write(bytes(raw))
        os.replace(p + ".tmp", p)
        assert ck.verify_checkpoint(d) == []  # same size: shallow passes
        deep = ck.verify_checkpoint(d, deep=True)
        assert deep and "crc32" in deep[0]
        # Restore itself fails closed on the rotten chunk — no silent
        # garbage weights even without an explicit --deep pass.
        with pytest.raises(ck.CheckpointError, match="crc"):
            ck.restore_tree(d)

    def test_coordinator_crash_between_acks_and_commit(self, tmp_path):
        """Chaos: every rank wrote + acked, the coordinator died before
        the manifest landed.  The previous committed step must restore
        bit-exact, the orphan stays invisible, and the next incarnation
        GCs it once a newer step commits."""
        root = str(tmp_path)
        prev_tree = _tree()
        _save_world(root, 0, 2, lambda r: prev_tree)
        mgr = CheckpointManager(root, ".", num_to_keep=None)
        # (manager roots at root/., i.e. root itself)
        mgr._register_entry({"path": step_dir(root, 0), "metrics": {},
                             "time": 0.0, "step": 0})

        # Step 1: both ranks write + ack... and the coordinator "dies"
        # (commit_ready never runs).
        d1 = step_dir(root, 1)
        for rank in range(2):
            snap = ck.snapshot_tree({"w": np.full(4, rank + 10.0)})
            index, blob = ck.build_shard(snap, rank, 2, 1)
            ck.write_shard(d1, index, blob,
                           skeleton_pkl=snap.skeleton_pkl if rank == 0
                           else None)
            mgr.note_ack({"step": 1, "rank": rank, "world": 2, "dir": d1,
                          "nbytes": len(blob), "crc32": index["crc32"],
                          "write_s": 0.0, "replica": False, "metrics": {}})
        del mgr  # crash before commit_ready()

        # Fresh coordinator incarnation: latest is still step 0, which
        # restores bit-exact; the orphan dir is not a checkpoint.
        mgr2 = CheckpointManager(root, ".", num_to_keep=None)
        assert mgr2.latest() == step_dir(root, 0)
        assert not ck.is_committed(d1)
        _tree_equal(ck.restore_tree(mgr2.latest()), prev_tree)

        # A later committed step GCs the orphan.
        d2 = _save_world(root, 2, 1, lambda r: {"w": np.zeros(2)})
        mgr2.note_ack({"step": 2, "rank": 0, "world": 1, "dir": d2,
                       "nbytes": 1, "crc32": 0, "write_s": 0.0,
                       "replica": False, "metrics": {}})
        # commit over an existing manifest is idempotent-ish: rebuild it.
        committed = mgr2.commit_ready()
        assert [m["step"] for m in committed] == [2]
        assert mgr2.latest() == d2
        assert not os.path.exists(d1), "orphan dir survived GC"
        assert os.path.exists(step_dir(root, 0)), \
            "committed dir must never be GC'd as an orphan"

    def test_numpy_scalar_metrics_commit_cleanly(self, tmp_path):
        """np.float32 (the normal type of a jax loss) in save metrics
        must not crash the coordinator's JSON manifest build."""
        root = str(tmp_path)
        mgr = CheckpointManager(root, ".", num_to_keep=None)
        d = _save_world(root, 0, 1, lambda r: {"w": np.ones(2)})
        mgr.note_ack({"step": 0, "rank": 0, "world": 1, "dir": d,
                      "nbytes": 1, "crc32": 0, "write_s": 0.0,
                      "replica": False,
                      "metrics": {"loss": np.float32(0.5), "n": np.int64(3),
                                  "arr": np.ones(4), "tag": "x"}})
        [manifest] = mgr.commit_ready()
        assert manifest["metrics"] == {"loss": 0.5, "n": 3, "tag": "x"}

    def test_stale_generation_acks_are_dropped(self, tmp_path):
        root = str(tmp_path)
        mgr = CheckpointManager(root, ".", num_to_keep=None)
        mgr.reset_pending_acks(generation=2)
        d = _save_world(root, 0, 1, lambda r: {"w": np.ones(2)})
        mgr.note_ack({"step": 0, "rank": 0, "world": 1, "dir": d,
                      "nbytes": 1, "crc32": 0, "write_s": 0.0,
                      "replica": False, "metrics": {}, "generation": 1})
        assert mgr.commit_ready() == []  # dead incarnation's straggler
        mgr.note_ack({"step": 0, "rank": 0, "world": 1, "dir": d,
                      "nbytes": 1, "crc32": 0, "write_s": 0.0,
                      "replica": False, "metrics": {}, "generation": 2})
        assert [m["step"] for m in mgr.commit_ready()] == [0]

    def test_explicit_step_cannot_overwrite_committed(self, tmp_path):
        from ray_tpu.checkpoint.manager import WorkerCheckpointClient
        root = str(tmp_path)
        _save_world(root, 3, 1, lambda r: {"w": np.ones(2)})
        client = WorkerCheckpointClient(
            run_id="x", rank=0, world_size=1, run_root=root,
            experiment="e")
        with pytest.raises(ck.CheckpointError, match="committed"):
            client.save({"w": np.zeros(2)}, step=3, sync=True)
        # The committed checkpoint is untouched.
        assert ck.verify_checkpoint(step_dir(root, 3), deep=True) == []

    def test_stale_replica_blob_falls_back_to_disk(self, tmp_path):
        from ray_tpu.checkpoint.manager import _validated_blobs
        root = str(tmp_path)
        d = _save_world(root, 0, 1, lambda r: {"w": np.ones(2)})
        manifest = ck.read_manifest(d)
        snap = ck.snapshot_tree({"w": np.full(2, 9.0)})  # divergent save
        stale_index, stale_blob = ck.build_shard(snap, 0, 1, 0)
        assert _validated_blobs({0: (stale_index, stale_blob)},
                                manifest) == {}
        # A blob matching the manifest passes through.
        ipath = os.path.join(d, manifest["shards"][0]["index_file"])
        good_index = json.loads(open(ipath).read())
        good_blob = open(os.path.join(
            d, manifest["shards"][0]["data_file"]), "rb").read()
        assert 0 in _validated_blobs({0: (good_index, good_blob)},
                                     manifest)

    def test_placement_over_legacy_layout_is_loud(self, tmp_path):
        from ray_tpu.checkpoint.manager import WorkerCheckpointClient
        d = str(tmp_path / "legacy")
        os.makedirs(d)
        ck.save_pytree({"w": np.ones((4, 2))}, d)
        client = WorkerCheckpointClient(
            run_id="x", rank=0, world_size=2, run_root=str(tmp_path),
            experiment="e")
        with pytest.raises(ck.CheckpointError, match="legacy"):
            client.load(d, placement=ck.even_placement(0, 0, 2))

    def test_incomplete_ack_set_never_commits(self, tmp_path):
        root = str(tmp_path)
        mgr = CheckpointManager(root, ".", num_to_keep=None)
        d = step_dir(root, 4)
        snap = ck.snapshot_tree({"w": np.ones(3)})
        index, blob = ck.build_shard(snap, 0, 2, 4)
        ck.write_shard(d, index, blob, skeleton_pkl=snap.skeleton_pkl)
        mgr.note_ack({"step": 4, "rank": 0, "world": 2, "dir": d,
                      "nbytes": len(blob), "crc32": index["crc32"],
                      "write_s": 0.0, "replica": False, "metrics": {}})
        assert mgr.commit_ready() == []
        assert mgr.latest() is None
        assert not ck.is_committed(d)


class TestAsyncWriter:
    def _job(self, tmp_path, step, payload_mb=0.0):
        n = max(1, int(payload_mb * 1024 * 256))
        snap = ck.snapshot_tree({"w": np.zeros(n, np.float32)})
        return ck.WriteJob(dirpath=step_dir(str(tmp_path), step),
                           step=step, rank=0, world=1, snapshot=snap)

    def test_backpressure_bounds_inflight(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAY_TPU_CKPT_TEST_WRITE_DELAY_S", "0.15")
        w = ck.AsyncCheckpointWriter(max_inflight=2)
        try:
            import time
            t0 = time.monotonic()
            waits = [w.submit(self._job(tmp_path, s)) for s in range(4)]
            assert w.inflight <= 4
            # First two admissions are free; later ones wait for slots.
            assert waits[0] < 0.1 and waits[1] < 0.1
            assert sum(waits) > 0.1, waits
            assert time.monotonic() - t0 < 3.0
        finally:
            monkeypatch.delenv("RAY_TPU_CKPT_TEST_WRITE_DELAY_S")
            w.close()
        for s in range(4):
            assert os.path.exists(
                os.path.join(step_dir(str(tmp_path), s),
                             "shard-00000-of-00001.bin"))

    def test_write_failure_surfaces_and_never_acks(self, tmp_path):
        acked = []
        job = self._job(tmp_path, 0)
        job.dirpath = os.path.join(str(tmp_path), "file_not_dir", "x")
        # Parent is a FILE: makedirs inside write_shard must fail.
        open(os.path.join(str(tmp_path), "file_not_dir"), "w").close()
        job.on_done = lambda *a: acked.append(a)
        w = ck.AsyncCheckpointWriter(max_inflight=1)
        w.submit(job)
        w.wait_idle(10.0)
        with pytest.raises(ck.CheckpointError, match="write failed"):
            w.raise_on_error()
        assert acked == []
        # The error surfaced ONCE; a transient failure must not poison
        # the writer for the rest of the run — close() is clean now.
        w.close()


class TestRetentionAndCopyAtomicity:
    def test_to_directory_replaces_existing_dest_atomically(self,
                                                            tmp_path):
        tree = _tree()
        d = _save_world(str(tmp_path / "run"), 0, 1, lambda r: tree)
        from ray_tpu.train import Checkpoint
        dest = str(tmp_path / "copy")
        os.makedirs(dest)
        with open(os.path.join(dest, "stale_garbage"), "w") as f:
            f.write("from an interrupted previous copy")
        out = Checkpoint(d).to_directory(dest)
        assert out == dest
        assert not os.path.exists(os.path.join(dest, "stale_garbage"))
        _tree_equal(ck.restore_tree(dest), tree)
        # No staging/old temp dirs left behind next to dest.
        leftovers = [n for n in os.listdir(str(tmp_path))
                     if ".tmp" in n or ".old" in n]
        assert leftovers == []

    def test_retention_deletes_victims_out_of_namespace(self, tmp_path):
        root = str(tmp_path)
        mgr = CheckpointManager(root, ".", num_to_keep=2)
        dirs = []
        for step in range(4):
            d = _save_world(root, step, 1, lambda r: {"s": step})
            dirs.append(d)
            mgr.note_ack({"step": step, "rank": 0, "world": 1, "dir": d,
                          "nbytes": 1, "crc32": 0, "write_s": 0.0,
                          "replica": False, "metrics": {}})
            mgr.commit_ready()
        assert not os.path.exists(dirs[0]) and not os.path.exists(dirs[1])
        assert os.path.exists(dirs[2]) and os.path.exists(dirs[3])
        assert mgr.latest() == dirs[3]
        # No half-deleted ".deleting-" husks left in the namespace.
        assert [n for n in os.listdir(root) if ".deleting-" in n] == []


class TestCkptCLI:
    def _run(self, *args):
        from click.testing import CliRunner
        from ray_tpu.scripts.cli import cli
        return CliRunner().invoke(cli, list(args))

    def test_ls_and_inspect(self, tmp_path):
        root = str(tmp_path)
        _save_world(root, 0, 2, lambda r: _tree())
        # One uncommitted in-flight dir rides along.
        d1 = step_dir(root, 1)
        snap = ck.snapshot_tree({"w": np.ones(2)})
        index, blob = ck.build_shard(snap, 0, 1, 1)
        ck.write_shard(d1, index, blob, skeleton_pkl=snap.skeleton_pkl)

        out = self._run("ckpt", "ls", root)
        assert out.exit_code == 0, out.output
        lines = out.output.splitlines()
        assert any("valid" in ln and ln.strip().startswith("0") for ln
                   in lines), out.output
        assert any("uncommitted" in ln for ln in lines), out.output

        out = self._run("ckpt", "inspect", root, "--deep")
        assert out.exit_code == 0, out.output
        assert "world:     2" in out.output
        assert "params/dense/kernel  float32[8x4]" in out.output
        assert "valid:     yes" in out.output

    def test_ls_flags_corruption_nonzero(self, tmp_path):
        root = str(tmp_path)
        d = _save_world(root, 0, 1, lambda r: _tree())
        [f] = [f for f in os.listdir(d) if f.endswith(".bin")]
        os.unlink(os.path.join(d, f))
        out = self._run("ckpt", "ls", root)
        assert out.exit_code == 1
        assert "INVALID" in out.output

    def test_missing_run_dir_is_loud(self, tmp_path):
        out = self._run("ckpt", "ls", str(tmp_path / "nope"))
        assert out.exit_code != 0
        assert "no run directory" in out.output


class TestLocalPin:
    def test_pin_chain_fetch_and_release(self, ray_start):
        """The object-store pin is readable back (fetch_local_pins), the
        KV chain keeps at most one pinned generation, and release
        retires the entry."""
        import pickle

        from ray_tpu._private.api import _control
        from ray_tpu.checkpoint import replica as rmod

        snap = ck.snapshot_tree({"w": np.arange(6, dtype=np.float32)})
        index, blob = ck.build_shard(snap, 0, 1, 0)
        pin = rmod.LocalPin("pin_exp", 0)
        pin.pin(blob, 0, index)
        manifest = {"step": 0, "shards": [{"rank": 0}]}
        got = rmod.fetch_local_pins("pin_exp", manifest)
        assert 0 in got and got[0][1] == blob

        # New generation replaces the entry: old step no longer served.
        index1, blob1 = ck.build_shard(snap, 0, 1, 1)
        pin.pin(blob1, 1, index1)
        assert rmod.fetch_local_pins("pin_exp", manifest) == {}
        got = rmod.fetch_local_pins("pin_exp",
                                    {"step": 1, "shards": [{"rank": 0}]})
        assert got[0][1] == blob1

        pin.release()
        assert _control("kv_get", rmod._pin_key("pin_exp", 0)) is None


# -- JaxTrainer e2e ---------------------------------------------------------


def _ckpt_train_fn(config):
    import os
    import time as _t

    import numpy as np

    import ray_tpu.train as train

    state = train.load_checkpoint()
    start = 0 if state is None else int(state["step"])
    w = np.zeros((8, 8), np.float32) if state is None else state["w"]
    for step in range(start, config["steps"]):
        _t.sleep(config.get("step_sleep_s", 0.0))
        w = w + 1.0
        train.save_checkpoint({"w": w, "step": step + 1},
                              metrics={"step": step})
        train.report({"loss": float(w.mean()), "step": step})
        marker = config.get("die_marker")
        if marker and config.get("die_at") == step and \
                not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)


class TestTrainerE2E:
    def test_kill_worker_mid_async_save(self, ray_start, tmp_path):
        """Chaos: the worker dies while its async save is still inside
        the (artificially slowed) writer.  The run must recover from the
        last COMMITTED step, every manifest on disk must verify, and the
        goodput tracker must book the lost window."""
        from ray_tpu.train import (FailureConfig, JaxTrainer, RunConfig,
                                   ScalingConfig)
        res = JaxTrainer(
            _ckpt_train_fn,
            train_loop_config={"steps": 4, "die_at": 2,
                               "step_sleep_s": 0.3,
                               "die_marker": str(tmp_path / "died")},
            scaling_config=ScalingConfig(
                num_workers=1,
                env_per_worker={
                    "RAY_TPU_CKPT_TEST_WRITE_DELAY_S": "0.4"}),
            run_config=RunConfig(
                name="ckpt_chaos", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1))).fit()
        assert res.error is None, res.error
        assert res.num_failures == 1
        # Every directory that claims to be a checkpoint verifies —
        # kill-mid-save can never leave a manifest that fails checksum.
        run_dir = str(tmp_path / "ckpt_chaos")
        recs = ck.scan_run_dir(run_dir, deep=True)
        committed = [r for r in recs if r["committed"]]
        assert committed, recs
        for r in committed:
            assert r["valid"], r
        # The final state round-trips and reflects a true resume: the
        # restored w equals step count (monotone +1 per step, no replay
        # divergence, no loss of committed work).
        state = res.checkpoint.load_pytree()
        assert float(state["w"][0, 0]) == float(state["step"])
        assert state["step"] == 4
        # The kill's window is booked as lost/restart, not goodput.
        assert res.goodput["phases_s"].get("lost", 0.0) > 0.0
        assert res.goodput["phases_s"].get("restart", 0.0) > 0.0

    def test_two_rank_sharded_save_then_world1_restore(self, ray_start,
                                                       tmp_path):
        """Resharding e2e through the trainer: two ranks save disjoint
        row blocks of one global array; a world-1 restore reassembles it
        bit-exact (the 2->1 leg of the acceptance matrix, on the real
        ack/commit path)."""
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def save_fn(config):
            import numpy as np

            import ray_tpu.checkpoint as ckm
            import ray_tpu.train as train
            ctx = train.get_context()
            rank, world = ctx.get_world_rank(), ctx.get_world_size()
            g = np.arange(64, dtype=np.float32).reshape(8, 8)
            (r0, r1), _ = ckm.even_shard(g.shape, 0, rank, world)

            def spec(key, leaf):
                if key == "w":
                    return g.shape, ckm.even_shard(g.shape, 0, rank,
                                                   world)
                return tuple(leaf.shape), ckm.full_index(leaf.shape)
            train.save_checkpoint({"w": g[r0:r1], "step": 1},
                                  shard_spec=spec)
            train.report({"step": 0})

        res = JaxTrainer(
            save_fn, train_loop_config={},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="reshard",
                                 storage_path=str(tmp_path))).fit()
        assert res.error is None, res.error
        assert res.checkpoint is not None
        manifest = res.checkpoint.manifest()
        assert manifest["world_size"] == 2
        assert len(manifest["shards"]) == 2
        out = res.checkpoint.load_pytree()
        assert np.array_equal(
            out["w"], np.arange(64, dtype=np.float32).reshape(8, 8))

    def test_emergency_replica_restore_from_memory(self, ray_start,
                                                   tmp_path):
        """Run 1 trains with replication on; run 2 (same experiment)
        restores — the shards come from the peer holder's RAM, counted
        on ray_tpu_ckpt_replica_restores_total."""
        from ray_tpu.train import (CheckpointConfig, JaxTrainer,
                                   RunConfig, ScalingConfig)
        from ray_tpu.util import metrics as mmod

        def base(steps):
            return JaxTrainer(
                _ckpt_train_fn, train_loop_config={"steps": steps},
                scaling_config=ScalingConfig(num_workers=1),
                run_config=RunConfig(
                    name="replica_e2e", storage_path=str(tmp_path),
                    checkpoint_config=CheckpointConfig(
                        emergency_replica=True)))

        assert base(2).fit().error is None

        def replica_count():
            for line in mmod.prometheus_text().splitlines():
                if line.startswith("ray_tpu_ckpt_replica_restores_total"):
                    return float(line.split()[-1])
            return 0.0

        before = replica_count()
        res2 = base(4).fit()
        assert res2.error is None, res2.error
        assert replica_count() > before, \
            "second run did not restore from the in-memory replica"
        state = res2.checkpoint.load_pytree()
        assert state["step"] == 4  # resumed at 2, ran to 4

    def test_goodput_reattributes_blocking_save_time(self, ray_start,
                                                     tmp_path):
        """Async saves book only their BLOCKING slice to the checkpoint
        phase — with background writes the checkpoint phase must stay a
        small fraction of productive step time."""
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

        def fn(config):
            import time as _t

            import jax
            import numpy as np

            import ray_tpu.train as train
            jax.numpy.zeros(1)  # a real train fn has jax warm already
            for step in range(3):
                _t.sleep(0.15)
                train.save_checkpoint(
                    {"w": np.zeros((64, 64), np.float32), "step": step})
                train.report({"step": step})

        res = JaxTrainer(
            fn, train_loop_config={},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="goodput_async",
                                 storage_path=str(tmp_path))).fit()
        assert res.error is None, res.error
        phases = res.goodput["phases_s"]
        ckpt_s = phases.get("checkpoint", 0.0)
        assert ckpt_s < 0.5 * phases.get("step", 0.0) + 0.05, phases
