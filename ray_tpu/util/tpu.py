"""TPU slice reservation: gang-reserve every host of one or more pod slices.

Reference: python/ray/util/tpu.py — SlicePlacementGroup:414,
slice_placement_group:662, get_tpu_worker_resources:135,
get_tpu_coordinator_env_vars:206 (MEGASCALE_* plumbing).

A slice reservation is a placement group with one bundle per TPU host in the
slice: bundle 0 additionally requests the ``TPU-{gen}-head`` marker resource
so exactly one reservation can claim a given slice's rank-0 host, and every
bundle requests that host's full chip count — the gang either gets the whole
slice or nothing (STRICT_SPREAD over hosts).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.accelerators.tpu import (_CHIPS_PER_HOST, TPUAcceleratorManager,
                                      get_tpu_coordinator_env_vars)


def get_num_tpu_chips_per_host(accelerator_type: str) -> int:
    gen = TPUAcceleratorManager.generation_from_type(accelerator_type)
    return _CHIPS_PER_HOST.get(gen, 4)


def get_tpu_worker_resources(accelerator_type: str) -> List[Dict[str, float]]:
    """Per-host bundle list for one slice of ``accelerator_type``
    (reference: util/tpu.py:135)."""
    num_hosts = TPUAcceleratorManager.num_hosts_for_type(accelerator_type)
    chips = get_num_tpu_chips_per_host(accelerator_type)
    gen = TPUAcceleratorManager.generation_from_type(accelerator_type)
    bundles: List[Dict[str, float]] = []
    for host in range(num_hosts):
        bundle: Dict[str, float] = {"TPU": float(chips)}
        if host == 0:
            bundle[f"TPU-{gen}-head"] = 1.0
        bundles.append(bundle)
    return bundles


@dataclass
class SlicePlacementGroup:
    """A reserved TPU slice (or multi-slice set) ready for gang scheduling.

    Reference: util/tpu.py:414.  ``placement_groups[i]`` reserves slice i;
    ``coordinator_env(slice_id)`` returns the MEGASCALE env for multi-slice
    jax.distributed formation over DCN.
    """

    accelerator_type: str
    num_slices: int = 1
    name: str = field(default_factory=lambda: f"tpu-slice-{uuid.uuid4().hex[:8]}")
    placement_groups: List[ray_tpu.PlacementGroup] = field(default_factory=list)
    _coordinator_port: int = 8476

    @property
    def num_hosts_per_slice(self) -> int:
        return TPUAcceleratorManager.num_hosts_for_type(self.accelerator_type)

    @property
    def chips_per_host(self) -> int:
        return get_num_tpu_chips_per_host(self.accelerator_type)

    @property
    def total_hosts(self) -> int:
        return self.num_hosts_per_slice * self.num_slices

    def ready(self, timeout: Optional[float] = 60.0) -> bool:
        return all(pg.ready(timeout=timeout) for pg in self.placement_groups)

    def coordinator_env(self, slice_id: int,
                        coordinator_host: str = "localhost") -> Dict[str, str]:
        return get_tpu_coordinator_env_vars(
            slice_id, self.num_slices,
            f"{coordinator_host}:{self._coordinator_port}")

    def slice_nodes(self, slice_index: int) -> List[str]:
        """Node ids (hex) currently holding slice ``slice_index``'s
        committed bundles (empty for a still-pending slice)."""
        from ray_tpu._private.ids import NodeID
        pg = self.placement_groups[slice_index]
        locs = pg.bundle_locations() or []
        return sorted({NodeID(b).hex() for b in locs if b})

    def drain_slice(self, slice_index: int, deadline_s: float = 30.0,
                    reason: str = "preemption") -> List[str]:
        """Slice-granular drain: fence + evacuate exactly ONE slice of a
        multi-slice reservation.  Every node holding this slice-PG's
        bundles gets a drain notice (unschedulable for new leases, kill
        deadline advertised); the OTHER slices' committed bundles are
        never touched — preempting one slice of a multi-slice job must
        not tear down the rest.  The train controller's drain poll sees
        the covered ranks and reshapes the mesh's dp axis across the
        surviving slices; the autoscaler's gang launcher pre-buys the
        whole-slice replacement.  Returns the drained node ids."""
        from ray_tpu._private.api import _control
        from ray_tpu.util import telemetry
        drained = [hexid for hexid in self.slice_nodes(slice_index)
                   if _control("drain_node", hexid, deadline_s, reason)]
        if drained:
            telemetry.inc("ray_tpu_slice_drains_total")
        return drained

    def remove(self) -> None:
        for pg in self.placement_groups:
            ray_tpu.remove_placement_group(pg)
        self.placement_groups = []


def slice_placement_group(accelerator_type: str, num_slices: int = 1,
                          strategy: str = "STRICT_SPREAD",
                          ) -> SlicePlacementGroup:
    """Reserve ``num_slices`` whole slices of ``accelerator_type``
    (reference: util/tpu.py:662).

    Each slice becomes one placement group so preempting/resizing one slice
    never tears down the others (the multi-slice elastic story).
    """
    pgs = [
        ray_tpu.placement_group(
            get_tpu_worker_resources(accelerator_type), strategy=strategy)
        for _ in range(num_slices)
    ]
    return SlicePlacementGroup(
        accelerator_type=accelerator_type, num_slices=num_slices,
        placement_groups=pgs)
