"""``ray-tpu`` CLI: cluster lifecycle, jobs, state, timeline.

Reference: python/ray/scripts/scripts.py (click CLI — ``ray start:799``,
``ray stop:1346``, ``ray status``, ``ray job submit/list/logs/stop``,
``ray timeline``, ``ray summary``).

Run as ``python -m ray_tpu.scripts.cli ...`` (or the ``ray-tpu`` console
script once installed).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import click

DEFAULT_ADDRESS_FILE = "/tmp/ray_tpu/head_address"


def _resolve_address(address):
    if address:
        return address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(DEFAULT_ADDRESS_FILE) as f:
            return json.load(f)["address"]
    except (FileNotFoundError, json.JSONDecodeError, KeyError):
        raise click.ClickException(
            "no head address found — pass --address, set RAY_TPU_ADDRESS, "
            "or run `ray-tpu start --head` on this machine")


def _client(address):
    from ray_tpu.job_submission import JobSubmissionClient
    return JobSubmissionClient(_resolve_address(address))


@click.group()
def cli():
    """ray_tpu cluster and job management."""


@cli.command()
@click.option("--head", is_flag=True, help="Start a head node.")
@click.option("--address", default=None,
              help="Join an existing cluster: head host:port "
                   "(from `ray-tpu start --head` output).")
@click.option("--port", type=int, default=8265, show_default=True)
@click.option("--node-port", type=int, default=6380, show_default=True,
              help="TCP port for cluster node joins (head only).")
@click.option("--token", default=None, help="Cluster auth token.")
@click.option("--num-cpus", type=float, default=None)
@click.option("--num-tpus", type=int, default=None)
@click.option("--address-file", default=DEFAULT_ADDRESS_FILE)
@click.option("--state-dir", default="/tmp/ray_tpu/head_state",
              help="Head state persistence dir ('' disables). A restarted "
                   "head replays it: actors/PGs/KV survive head death.")
@click.option("--block", is_flag=True, help="Run in the foreground.")
def start(head, address, port, node_port, token, num_cpus, num_tpus,
          address_file, state_dir, block):
    """Start a head node, or join a cluster with --address=<host:port>
    (reference: ray start / ray start --address)."""
    if not head and not address:
        raise click.ClickException("pass --head or --address=<host:port>")
    if address:
        # Worker-node join path: runs the NodeServer in the foreground
        # (or detached without --block).
        if not token:
            # Same-host join: the head persisted its token (0600) in the
            # address file; remote joins must pass --token explicitly.
            try:
                with open(address_file) as f:
                    token = json.load(f)["token"]
            except (FileNotFoundError, KeyError, json.JSONDecodeError):
                raise click.ClickException(
                    "no cluster token: pass --token (the head persists its "
                    "token in the address file on its own machine)")
        cmd = [sys.executable, "-m", "ray_tpu._private.node_server_main",
               "--address", address]
        if token:
            cmd += ["--token", token]
        if num_cpus is not None:
            cmd += ["--num-cpus", str(num_cpus)]
        if num_tpus is not None:
            cmd += ["--num-tpus", str(num_tpus)]
        if block:
            raise SystemExit(subprocess.call(cmd))
        log_f = open(os.path.join("/tmp", "ray_tpu_node.log"), "ab")
        proc = subprocess.Popen(cmd, start_new_session=True,
                                stdin=subprocess.DEVNULL, stdout=log_f,
                                stderr=subprocess.STDOUT)
        log_f.close()
        click.echo(f"node joining {address} (pid {proc.pid})")
        return
    cmd = [sys.executable, "-m", "ray_tpu.scripts.head",
           "--port", str(port), "--node-port", str(node_port),
           "--address-file", address_file, "--state-dir", state_dir]
    if token:
        cmd += ["--token", token]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    if block:
        raise SystemExit(subprocess.call(cmd))
    try:
        os.unlink(address_file)
    except FileNotFoundError:
        pass
    # Detach stdio: the head must not hold the CLI's stdout/stderr pipes
    # open (callers capturing our output would block on EOF forever).
    log_path = address_file + ".log"
    log_f = open(log_path, "ab")
    proc = subprocess.Popen(cmd, start_new_session=True,
                            stdin=subprocess.DEVNULL, stdout=log_f,
                            stderr=subprocess.STDOUT)
    log_f.close()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise click.ClickException(
                f"head process exited early with code {proc.returncode}")
        try:
            with open(address_file) as f:
                address = json.load(f)["address"]
            click.echo(f"head started at {address} (pid {proc.pid})")
            return
        except (FileNotFoundError, json.JSONDecodeError):
            time.sleep(0.2)
    raise click.ClickException("head did not start within 30s")


@cli.command()
@click.option("--address-file", default=DEFAULT_ADDRESS_FILE)
def stop(address_file):
    """Stop the head process started with ``ray-tpu start``."""
    import signal

    try:
        with open(address_file) as f:
            pid = json.load(f)["pid"]
    except (FileNotFoundError, json.JSONDecodeError, KeyError):
        raise click.ClickException("no running head found")
    try:
        os.kill(pid, signal.SIGTERM)
        click.echo(f"sent SIGTERM to head (pid {pid})")
    except ProcessLookupError:
        click.echo("head already gone")
        try:
            os.unlink(address_file)
        except FileNotFoundError:
            pass


@cli.command()
@click.option("--address", default=None)
def status(address):
    """Cluster resources, nodes, actors, task summary."""
    s = _client(address).cluster_status()
    click.echo(f"nodes: {len(s['nodes'])}")
    for n in s["nodes"]:
        if not n["alive"]:
            state = "DEAD"
        elif n.get("draining"):
            state = (f"DRAINING({n.get('drain_remaining_s', 0):.0f}s "
                     f"{n.get('drain_reason') or 'drain'})")
        else:
            state = "ALIVE"
        click.echo(f"  {n['node_id'][:12]} {state} head={n['is_head']} "
                   f"{n['hostname']}")
    click.echo("resources (available/total):")
    total, avail = s["total_resources"], s["available_resources"]
    for k in sorted(total):
        click.echo(f"  {k}: {avail.get(k, 0):g}/{total[k]:g}")
    alive = sum(1 for a in s["actors"] if a["state"] == "ALIVE")
    click.echo(f"actors: {alive} alive / {len(s['actors'])} total")
    if s["task_summary"]:
        click.echo("tasks:")
        for name, states in sorted(s["task_summary"].items()):
            parts = ", ".join(f"{k}={v}" for k, v in sorted(states.items()))
            click.echo(f"  {name}: {parts}")
    # Operator health at a glance: live goodput + last watchdog verdict
    # (no dashboard curl needed).
    g = s.get("goodput")
    if g:
        click.echo(f"train goodput: {g['goodput_ratio']:.3f} "
                   f"(productive {g['productive_s']:.1f}s / "
                   f"total {g['total_s']:.1f}s)")
    else:
        click.echo("train goodput: n/a (no training run observed)")
    # Pending pre-buys belong next to the goodput they protect: a
    # non-zero count means replacements are already booting for noticed
    # preemptions / a goodput sag.
    a = s.get("autoscaler")
    if a:
        pol = a.get("policy") or {}
        wg = pol.get("windowed_goodput")
        click.echo(
            f"autoscaler: pending pre-buys {a.get('pending_prebuys', 0)} "
            f"(bought {a.get('prebuy_total', 0)} total, "
            f"idle-draining {a.get('idle_draining', 0)}"
            + (f", windowed goodput {wg:.3f}" if wg is not None else "")
            + ")")
    else:
        click.echo("autoscaler: n/a (no autoscaler attached)")
    m = s.get("mesh")
    if m:
        click.echo(f"train mesh: {m.get('descriptor')} "
                   f"(world {m.get('world')} x "
                   f"{m.get('devices_per_worker')} devices)")
    else:
        click.echo("train mesh: n/a (no mesh-parallel run observed)")
    w = s.get("watchdog")
    if w:
        if w.get("status") == "ok":
            click.echo("watchdog: ok")
        else:
            click.echo(f"watchdog: {w['status']} rank={w.get('rank')} "
                       f"(stragglers={w.get('straggler_total', 0)}, "
                       f"hangs={w.get('hang_total', 0)})")
    else:
        click.echo("watchdog: n/a (no watchdog verdict recorded)")


@cli.command()
@click.option("--address", default=None)
@click.option("--decisions", "-n", "num_decisions", type=int, default=0,
              help="Also print the last N scheduler decision records.")
def sched(address, num_decisions):
    """Live control-plane view: scheduler queue depths, decision rates
    and totals by kind, and task-event ring health (dropped events /
    fold backlog) — the first thing to look at when submissions pile
    up.  `ray-tpu task why <id>` digs into one task."""
    from urllib.parse import urlencode
    client = _client(address)
    path = "/api/cluster/sched"
    if num_decisions:
        path += "?" + urlencode({"decisions": num_decisions})
    out = client._request("GET", path)
    s = out["stats"]
    r, d = s["rates"], s["decisions"]
    click.echo(f"decisions/s: {r['decisions_per_s_5s']:g} (5s)  "
               f"{r['decisions_per_s_60s']:g} (60s)   "
               f"total {d['total']}"
               + (f"  RING DROPPED {d['num_dropped']}"
                  if d["num_dropped"] else ""))
    if d["counts"]:
        kinds = ", ".join(f"{k}={v}" for k, v in sorted(d["counts"].items()))
        click.echo(f"  by kind: {kinds}")
    click.echo("queues:")
    for q, depth in sorted(s["queues"].items()):
        click.echo(f"  {q}: {depth}")
    ev = s["events"]
    click.echo(f"task events: {ev['num_events']}/{ev['capacity']} "
               f"(dropped {ev['num_dropped']}, "
               f"fold backlog {ev['fold_backlog']})")
    n = s["nodes"]
    click.echo(f"nodes: {n['total']} ({n['draining']} draining)")
    for rec in out.get("decisions", []):
        rej = "".join(f" {k}:{v}" for k, v in rec["rejected"].items())
        # Full task id: ids share the job-id prefix, so a truncated id
        # would be ambiguous when pasted into `ray-tpu task why`.
        click.echo(f"  [{rec['kind']:>10}] {rec['task_id'] or '-'} "
                   f"{rec['name'] or '':.24} attempt={rec['attempt']} "
                   f"cands={rec['candidates']} "
                   f"node={(rec['node_id'] or '-'):.12}{rej}")


@cli.group()
def task():
    """Task-level introspection (control-plane telescope)."""


@task.command("why")
@click.option("--address", default=None)
@click.argument("task_id")
def task_why(address, task_id):
    """Explain TASK_ID (hex, prefix ok): why it is still pending —
    unresolved deps by ObjectID, the closest-fit node and its resource
    gap, the drain fence or missing placement-group bundle rejecting it
    — or, once placed, why it landed on its node."""
    from urllib.parse import urlencode
    client = _client(address)
    out = client._request(
        "GET", "/api/cluster/task_explain?" + urlencode(
            {"task_id": task_id}))
    status = out.get("status", "unknown")
    if status == "ambiguous":
        raise click.ClickException(
            f"ambiguous task prefix {task_id!r}:\n  "
            + "\n  ".join(out.get("matches", [])))
    click.echo(f"task {out['task_id']} "
               f"{out.get('name') or ''}".rstrip())
    click.echo(f"status: {status}")
    if status == "unknown":
        click.echo(f"  {out.get('detail', 'not found')}")
        raise SystemExit(1)
    if out.get("reasons"):
        click.echo("reasons: " + ", ".join(out["reasons"]))
    for dep in out.get("unresolved_deps", []):
        click.echo(f"  waiting on object {dep[:16]}")
    cf = out.get("closest_fit")
    if cf:
        gap = ", ".join(f"{k} short {v:g}" for k, v in cf["gap"].items()) \
            or "fits (queued behind the scheduler loop)"
        click.echo(f"closest fit: node {cf['node_id'][:12]} — {gap}")
    pg = out.get("pg")
    if pg:
        click.echo(f"placement group {pg['placement_group_id'][:12]} "
                   f"bundle {pg['bundle_index']}: committed bundles "
                   f"{pg['committed_bundles'] or 'none'}")
    if out.get("node_id"):
        click.echo(f"node: {out['node_id'][:12]}")
    dec = out.get("last_decision")
    if dec:
        rej = "".join(f" {k}:{v}" for k, v in dec["rejected"].items())
        click.echo(f"last decision: {dec['kind']} "
                   f"attempt={dec['attempt']} cands={dec['candidates']} "
                   f"class[{dec['sched_class']}]"
                   f"{' node=' + dec['node_id'][:12] if dec['node_id'] else ''}"
                   f"{rej}")
    waits = out.get("stage_waits") or {}
    if waits:
        click.echo("stage waits: " + ", ".join(
            f"{k}={v * 1e3:.1f}ms" for k, v in waits.items()))
    if out.get("error_message"):
        click.echo(f"error: {out['error_message']}")


@cli.command()
@click.option("--address", default=None)
@click.option("--top", "top_n", type=int, default=10, show_default=True,
              help="Top-N objects by size to list.")
def memory(address, top_n):
    """Cluster-wide object-store occupancy (data-plane telescope):
    per-node used/capacity/pinned/spilled bytes, op tallies, the top
    objects by size attributed to their owner node and producing task,
    and leak candidates.  `ray-tpu obj why <id>` digs into one object."""
    from urllib.parse import urlencode
    client = _client(address)
    out = client._request(
        "GET", "/api/cluster/memory?" + urlencode({"top_n": top_n}))
    t = out["totals"]
    click.echo(f"total: used {_fmt_bytes(t['used_bytes'])} / "
               f"{_fmt_bytes(t['capacity_bytes'])}  "
               f"pinned {_fmt_bytes(t['pinned_bytes'])}  "
               f"spilled {_fmt_bytes(t['spilled_bytes'])}  "
               f"objects {t['num_objects']} "
               f"({t['num_pinned']} pinned, {t['num_spilled']} spilled)")
    click.echo("nodes:")
    for nhex, sub in sorted(out["nodes"].items()):
        kind = "native" if sub.get("native") else "python"
        click.echo(f"  {nhex[:12]}  "
                   f"used {_fmt_bytes(sub.get('used_bytes', 0))}"
                   f"/{_fmt_bytes(sub.get('capacity_bytes', 0))}  "
                   f"pinned {_fmt_bytes(sub.get('pinned_bytes', 0))}  "
                   f"spilled {_fmt_bytes(sub.get('spilled_bytes', 0))}  "
                   f"objects {sub.get('num_objects', 0)}  [{kind}]")
    ops = {}
    for sub in out["nodes"].values():
        for k, v in (sub.get("counts") or {}).items():
            ops[k] = ops.get(k, 0) + v
    if ops:
        click.echo("ops: " + ", ".join(f"{k}={v}"
                                       for k, v in sorted(ops.items())))
    if out.get("top_objects"):
        click.echo("top objects:")
        for o in out["top_objects"]:
            extra = ""
            if o.get("store_state"):
                extra = f"  state={o['store_state']} pins={o.get('pins', 0)}"
            # Full object id: paste into `ray-tpu obj why`.
            click.echo(f"  {o['object_id']}  "
                       f"{_fmt_bytes(o['size_bytes']):>10}  "
                       f"node={(o.get('node_id') or '-')[:12]} "
                       f"task={(o.get('task_id') or '-')[:12]}{extra}")
    leaks = out.get("leak_candidates") or []
    if leaks:
        click.echo("leak candidates:")
        for rec in leaks:
            click.echo(f"  {rec['object_id']}  "
                       f"{_fmt_bytes(rec.get('nbytes', 0)):>10}  "
                       f"{rec['reason']}  reads={rec.get('reads', 0)} "
                       f"pins={rec.get('pins', 0)} "
                       f"node={(rec.get('node_id') or '-')[:12]}")


@cli.group()
def obj():
    """Object-level introspection (data-plane telescope)."""


@obj.command("why")
@click.option("--address", default=None)
@click.argument("object_id")
def obj_why(address, object_id):
    """Explain OBJECT_ID (hex, prefix ok): where it lives (directory
    descriptor + owner node), which task produced it, and its store
    lifecycle — spills/restores, what localizing it cost, pins and who
    holds them."""
    from urllib.parse import urlencode
    client = _client(address)
    out = client._request(
        "GET", "/api/cluster/object_explain?" + urlencode(
            {"object_id": object_id}))
    status = out.get("status", "unknown")
    if status == "ambiguous":
        raise click.ClickException(
            f"ambiguous object prefix {object_id!r}:\n  "
            + "\n  ".join(out.get("matches", [])))
    if status == "unknown":
        click.echo(f"object {object_id}: unknown")
        click.echo(f"  {out.get('detail', 'not found')}")
        raise SystemExit(1)
    click.echo(f"object {out['object_id']}")
    if out.get("owner_task_id"):
        click.echo(f"owner task: {out['owner_task_id']}")
    d = out.get("directory")
    if d:
        size = _fmt_bytes(d["size_bytes"]) \
            if d.get("size_bytes") is not None else "?"
        click.echo(f"directory: {d['state']}  "
                   f"node={(d.get('node_id') or '?')[:12]}  size={size}"
                   + ("  ERROR-PAYLOAD" if d.get("error") else ""))
    else:
        click.echo("directory: gone (deleted, or never escaped its worker)")
    loc = out.get("local")
    if loc:
        click.echo(f"store: state={loc['state']}  "
                   f"size={_fmt_bytes(loc.get('nbytes') or 0)}  "
                   f"age={loc.get('age_s', 0):.1f}s  "
                   f"reads={loc.get('reads', 0)}")
        if loc.get("pins"):
            click.echo(f"  pinned {loc['pins']}x by: "
                       + ", ".join(loc.get("pinners") or ["?"]))
        if loc.get("spills") or loc.get("restores"):
            click.echo(f"  spills={loc.get('spills', 0)} "
                       f"restores={loc.get('restores', 0)}"
                       + ("  (currently on disk)"
                          if loc.get("spilled") else ""))
        if loc.get("pulls"):
            click.echo(f"  pulls={loc['pulls']} "
                       f"({_fmt_bytes(loc.get('pull_bytes', 0))}, "
                       f"avg {loc.get('pull_avg_ms', 0):.2f}ms) "
                       f"last peer {loc.get('last_peer') or '?'}")
        if loc.get("pushes"):
            click.echo(f"  pushes={loc['pushes']} "
                       f"({_fmt_bytes(loc.get('push_bytes', 0))})")
        events = loc.get("events") or []
        if events:
            click.echo("events:")
            for ev in events[-12:]:
                peer = f" peer={ev['peer']}" if ev.get("peer") else ""
                det = f" [{ev['detail']}]" if ev.get("detail") else ""
                click.echo(f"  {ev['kind']:>8}  "
                           f"{_fmt_bytes(ev.get('nbytes') or 0):>10}"
                           f"{peer}{det}")
    ov = out.get("owner_view")
    if ov:
        click.echo(f"owner node view: state={ov.get('state')} "
                   f"pins={ov.get('pins', 0)} "
                   f"size={_fmt_bytes(ov.get('nbytes', 0))}")


@cli.group()
def metrics():
    """Metrics history + windowed queries (ray_tpu.metricsview)."""


@metrics.command("query")
@click.option("--address", default=None)
@click.option("--window", "window_s", type=float, default=60.0,
              show_default=True, help="Window length in seconds.")
@click.option("--agg", default="avg", show_default=True,
              help="rate | delta | avg | min | max | last | pNN "
                   "(pNN, e.g. p99, reconstructs the WINDOW's "
                   "percentile from histogram bucket deltas).")
@click.option("--tag", "tag_pairs", multiple=True, metavar="K=V",
              help="Tag filter (repeatable); unmatched tag sets are "
                   "aggregated.")
@click.argument("name")
def metrics_query(address, window_s, agg, tag_pairs, name):
    """Windowed aggregate of series NAME from the head's time-series
    store, e.g.

        ray-tpu metrics query ray_tpu_serve_request_latency_seconds
        --window 60 --agg p99
    """
    from urllib.parse import urlencode
    params = [("name", name), ("window", window_s), ("agg", agg)]
    params += [("tag", t) for t in tag_pairs]
    out = _client(address)._request(
        "GET", "/api/cluster/metrics/query?" + urlencode(params))
    value = out.get("value")
    shown = "no data" if value is None else f"{value:g}"
    click.echo(f"{out['name']} {out['agg']} over {out['window_s']:g}s: "
               f"{shown}")
    click.echo(f"  series matched: {out['series']}  "
               f"points in window: {out['points']}")


_SPARK = "▁▂▃▄▅▆▇█"


@metrics.command("history")
@click.option("--address", default=None)
@click.option("--window", "window_s", type=float, default=300.0,
              show_default=True)
@click.option("--points", "max_points", type=int, default=60,
              show_default=True, help="Max points per series.")
@click.option("--tag", "tag_pairs", multiple=True, metavar="K=V")
@click.option("--raw", is_flag=True,
              help="Print [age_s, value] rows instead of sparklines.")
@click.argument("name")
def metrics_history(address, window_s, max_points, tag_pairs, name, raw):
    """Recent stored points of series NAME (per tag set) as a terminal
    sparkline — histogram series render per-interval average latency."""
    from urllib.parse import urlencode
    params = [("name", name), ("window", window_s),
              ("points", max_points)]
    params += [("tag", t) for t in tag_pairs]
    out = _client(address)._request(
        "GET", "/api/cluster/metrics/history?" + urlencode(params))
    if not out["series"]:
        click.echo("no stored points")
        return
    for series in out["series"]:
        tags = ",".join(f"{k}={v}" for k, v in
                        sorted(series["tags"].items()))
        label = f"{out['name']}{{{tags}}}" if tags else out["name"]
        vals = [v for _age, v in series["points"] if v is not None]
        if raw or not vals:
            click.echo(f"{label} ({series['type']}):")
            for age, v in series["points"]:
                click.echo(f"  -{age:g}s  {'-' if v is None else v}")
            continue
        lo, hi = min(vals), max(vals)
        span = (hi - lo) or 1.0
        line = "".join(
            " " if v is None else
            _SPARK[min(len(_SPARK) - 1,
                       int((v - lo) / span * (len(_SPARK) - 1)))]
            for _age, v in series["points"])
        oldest = series["points"][0][0]
        click.echo(f"{label} ({series['type']}, last {oldest:g}s)  "
                   f"min={lo:g} max={hi:g}")
        click.echo(f"  {line}")


@metrics.command("series")
@click.option("--address", default=None)
def metrics_series(address):
    """Series names with stored history."""
    for name in _client(address)._request(
            "GET", "/api/cluster/metrics/series"):
        click.echo(name)


@cli.command()
@click.option("--address", default=None)
@click.option("--recent", type=int, default=20, show_default=True,
              help="Transition-history rows to print.")
def alerts(address, recent):
    """SLO burn-rate alert states (ray_tpu.metricsview.slo): one row
    per objective (ok | pending | firing | resolved) with fast/slow
    burn rates, then the recent transition log."""
    from urllib.parse import urlencode
    out = _client(address)._request(
        "GET", "/api/cluster/alerts?" + urlencode({"recent": recent}))
    objs = out.get("objectives", [])
    if not objs:
        click.echo("no SLO objectives registered "
                   "(state.slo_set / `ray-tpu slo set`)")
        return
    click.echo(f"firing: {out['firing']}/{len(objs)}")
    for o in objs:
        mark = {"ok": " ", "pending": "~", "firing": "!",
                "resolved": "^"}.get(o["state"], "?")
        vf = "-" if o["value_fast"] is None else f"{o['value_fast']:g}"
        click.echo(
            f" {mark} [{o['state']:>8}] {o['objective']}: "
            f"{o['metric']} {o['agg']} {o['op']} {o['threshold']:g} "
            f"(now {vf}; burn fast {o['burn_fast']:g} / "
            f"slow {o['burn_slow']:g})"
            + (" [no data]" if o.get("no_data") else ""))
    trans = out.get("transitions", [])
    if trans:
        click.echo("recent transitions:")
        for t in trans:
            click.echo(f"  -{t['age_s']:g}s  {t['objective']}: "
                       f"{t['from']} -> {t['to']} "
                       f"(fast burn {t['burn_fast']:g})")


@cli.group()
def slo():
    """SLO objective management (see `ray-tpu alerts`)."""


@slo.command("list")
@click.option("--address", default=None)
def slo_list(address):
    for spec in _client(address)._request("GET", "/api/cluster/slo"):
        tags = ",".join(f"{k}={v}" for k, v in
                        sorted(spec.get("tags", {}).items()))
        click.echo(f"{spec['name']}: {spec['metric']}"
                   f"{'{' + tags + '}' if tags else ''} {spec['agg']} "
                   f"{spec['op']} {spec['threshold']:g} "
                   f"(fast {spec['fast_window_s']:g}s / "
                   f"slow {spec['slow_window_s']:g}s, "
                   f"cooldown {spec['cooldown_s']:g}s)")


@slo.command("set")
@click.option("--address", default=None)
@click.argument("objectives_file", type=click.Path(exists=True))
def slo_set(address, objectives_file):
    """Replace the SLO objective set from a JSON file (a list of
    objective specs; see ray_tpu.metricsview.SloObjective)."""
    with open(objectives_file) as f:
        specs = json.load(f)
    out = _client(address)._request("POST", "/api/cluster/slo", specs)
    click.echo(f"registered {out['objectives']} objective(s)")


@cli.group()
def serve():
    """Serving-plane introspection (decode fleets)."""


@serve.command("status")
@click.option("--address", default=None)
def serve_status(address):
    """Decode-fleet status: per-replica ongoing/queue/KV occupancy and
    prefix-cache hit rate, routing outcome counters, and the
    autoscaler's live signals/cooldown."""
    out = _client(address)._request("GET", "/api/cluster/serve/fleet")
    fleets = out.get("fleets") or []
    if not fleets:
        click.echo("no serving fleets published")
        return
    for f in fleets:
        reps = f.get("replicas") or []
        click.echo(f"fleet {f.get('name')}: {len(reps)} replica(s) "
                   f"(target {f.get('target_replicas')}), "
                   f"router queue {f.get('router_queue', 0)}, "
                   f"completed {f.get('completed', 0)}, "
                   f"shed {f.get('shed', 0)}")
        pf = f.get("prefix") or {}
        scales = f.get("scales") or {}
        click.echo(f"  routing: full={pf.get('full', 0)} "
                   f"partial={pf.get('partial', 0)} "
                   f"miss={pf.get('miss', 0)} "
                   f"rebalances={f.get('rebalances', 0)}  "
                   f"scales: up={scales.get('up', 0)} "
                   f"down={scales.get('down', 0)}")
        for r in reps:
            cache = r.get("cache") or {}
            hr = cache.get("hit_rate")
            click.echo(
                f"  {r.get('name')}  [{r.get('state')}]  "
                f"ongoing={r.get('ongoing', 0)} "
                f"waiting={r.get('waiting', 0)} "
                f"assigned={r.get('assigned', 0)}  "
                f"kv={float(r.get('kv_occupancy') or 0.0):.0%}  "
                f"cache={cache.get('entries', 0)} entries/"
                f"{_fmt_bytes(cache.get('bytes', 0))} "
                f"hit_rate={'-' if hr is None else format(hr, '.0%')}")
        a = f.get("autoscale")
        if a:
            sig = a.get("signals") or {}

            def _fmt(v, spec=".2f"):
                return "-" if v is None else format(float(v), spec)

            click.echo(
                f"  autoscale: queue/replica="
                f"{_fmt(sig.get('queue_per_replica'))} "
                f"shed_rate={_fmt(sig.get('shed_rate'))} "
                f"itl_p99={_fmt(sig.get('itl_p99_ms'), '.1f')}ms  "
                f"burning={_fmt(a.get('burning_for_s'), '.1f')}s "
                f"idle={_fmt(a.get('idle_for_s'), '.1f')}s "
                f"cooldown={_fmt(a.get('cooldown_remaining_s'), '.1f')}s"
                f"  bounds=[{a.get('min_replicas')},"
                f"{a.get('max_replicas')}]")


@cli.group()
def job():
    """Job submission and management."""


@job.command("submit")
@click.option("--address", default=None)
@click.option("--submission-id", default=None)
@click.option("--no-wait", is_flag=True)
@click.option("--env", "env_vars", multiple=True,
              help="KEY=VALUE env for the entrypoint (repeatable).")
@click.argument("entrypoint", nargs=-1, required=True)
def job_submit(address, submission_id, no_wait, env_vars, entrypoint):
    """Submit ENTRYPOINT (a shell command) as a supervised job."""
    client = _client(address)
    runtime_env = None
    if env_vars:
        pairs = dict(e.split("=", 1) for e in env_vars)
        runtime_env = {"env_vars": pairs}
    sid = client.submit_job(entrypoint=" ".join(entrypoint),
                            submission_id=submission_id,
                            runtime_env=runtime_env)
    click.echo(f"submitted job {sid}")
    if no_wait:
        return
    for chunk in client.tail_job_logs(sid):
        click.echo(chunk, nl=False)
    status_ = client.get_job_status(sid)
    click.echo(f"\njob {sid} finished: {status_}")
    if status_ != "SUCCEEDED":
        raise SystemExit(1)


@job.command("list")
@click.option("--address", default=None)
def job_list(address):
    for info in _client(address).list_jobs():
        click.echo(f"{info['submission_id']}  {info['status']:<10} "
                   f"{info['entrypoint']}")


@job.command("status")
@click.option("--address", default=None)
@click.argument("submission_id")
def job_status(address, submission_id):
    click.echo(_client(address).get_job_status(submission_id))


@job.command("logs")
@click.option("--address", default=None)
@click.argument("submission_id")
def job_logs(address, submission_id):
    click.echo(_client(address).get_job_logs(submission_id), nl=False)


@job.command("stop")
@click.option("--address", default=None)
@click.argument("submission_id")
def job_stop(address, submission_id):
    stopped = _client(address).stop_job(submission_id)
    click.echo("stopped" if stopped else "already finished")


@cli.command()
@click.option("--address", default=None)
@click.option("--output", "-o", default="timeline.json", show_default=True)
def timeline(address, output):
    """Dump the chrome-trace timeline to a file."""
    client = _client(address)
    trace = client._request("GET", "/api/cluster/timeline")
    with open(output, "w") as f:
        json.dump(trace, f)
    click.echo(f"wrote {len(trace)} events to {output}")


@cli.command()
@click.option("--address", default=None)
@click.option("--timeout", type=float, default=None,
              help="Seconds to wait for worker stack replies.")
@click.option("--output", "-o", default=None,
              help="Write the raw JSON dump to a file instead of "
                   "pretty-printing.")
def stack(address, timeout, output):
    """Print every live worker's Python stacks (reference: `ray stack`) —
    the first thing to run when a job looks stuck: it names the rank, the
    task, and the exact line each thread is blocked on."""
    client = _client(address)
    path = "/api/cluster/stacks"
    if timeout is not None:
        path += f"?timeout_s={timeout}"
    dump = client._request("GET", path)
    if output:
        with open(output, "w") as f:
            json.dump(dump, f, indent=1)
        click.echo(f"wrote {len(dump.get('stacks', []))} process records "
                   f"to {output}")
        return
    from ray_tpu._private.diagnostics import format_stack_dump
    click.echo(format_stack_dump(dump))


@cli.command()
@click.argument("paths", nargs=-1)
@click.option("--format", "fmt",
              type=click.Choice(["text", "json", "github"]),
              default="text", show_default=True)
@click.option("--list-rules", is_flag=True,
              help="Print the rule catalog and exit.")
@click.option("--explain", "explain_rule", metavar="RULE", default=None,
              help="Print one rule's rationale, a bad/good example and "
                   "the suppression syntax, then exit.")
@click.option("--internal/--no-internal", "internal", default=None,
              help="Force framework-internal rules on/off (default: "
                   "auto-detect per file — on for files inside a "
                   "ray_tpu package tree).")
@click.option("--changed", is_flag=True,
              help="Lint only files modified per git diff (plus "
                   "untracked .py files) — the fast pre-commit run.")
@click.option("--base", default="HEAD", show_default=True,
              metavar="REF", help="Diff base ref for --changed.")
@click.option("--lock-report", "lock_report", metavar="FILE",
              default=None,
              help="Print the top-contended-locks table from a "
                   "lock_contention.json (flight-recorder bundle or "
                   "RAY_TPU_LOCK_PROFILE=1 dump), then exit.")
@click.option("--sync-report", "sync_report", metavar="FILE",
              default=None,
              help="Print the hottest implicit host-sync sites from a "
                   "sync_findings.json (flight-recorder bundle or "
                   "RAY_TPU_SYNC_DEBUG=1 dump), then exit.")
def lint(paths, fmt, list_rules, explain_rule, internal, changed, base,
         lock_report, sync_report):
    """Framework-aware static analysis (see README "Static analysis").

    Checks user code for ray_tpu anti-patterns (blocking get() inside
    @remote, get()-in-a-loop, bad captures, actor self-calls) and — on
    the framework's own tree — internal invariants (no blocking under a
    lock, no swallowed control-plane exceptions, monotonic durations,
    telemetry catalog names, protocol handler completeness, and the
    RT4xx guarded-by/lock-discipline family).  Exits non-zero when
    findings remain; suppress a line with `# ray-tpu: noqa[RULE]`.
    """
    from ray_tpu.devtools import lint as lint_mod
    if list_rules:
        click.echo(lint_mod.rule_catalog_text())
        return
    if explain_rule is not None:
        text = lint_mod.explain_text(explain_rule)
        if text is None:
            click.echo(f"unknown rule {explain_rule!r} "
                       f"(see --list-rules)")
            raise SystemExit(1)
        click.echo(text)
        return
    if lock_report is not None:
        from ray_tpu.devtools import lockdebug
        try:
            with open(lock_report, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            click.echo(f"cannot read lock report {lock_report!r}: {e}")
            raise SystemExit(2)
        click.echo(lockdebug.format_contention(doc))
        return
    if sync_report is not None:
        from ray_tpu.devtools import syncdebug
        try:
            with open(sync_report, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            click.echo(f"cannot read sync report {sync_report!r}: {e}")
            raise SystemExit(2)
        click.echo(syncdebug.format_sync(doc))
        return
    if changed:
        try:
            files = lint_mod.changed_python_files(base=base)
        except RuntimeError as e:
            click.echo(f"--changed: {e}")
            raise SystemExit(2)
        if paths:
            roots = [os.path.abspath(p) for p in paths]
            files = [f for f in files
                     if any(f == r or f.startswith(r + os.sep)
                            for r in roots)]
        if not files:
            click.echo("0 finding(s) in 0 file(s) (no changed .py "
                       "files)")
            return
        paths = tuple(files)
    elif not paths:
        paths = (".",)
    result = lint_mod.lint_paths(list(paths), internal=internal)
    if fmt == "json":
        click.echo(lint_mod.format_json(result))
    elif fmt == "github":
        out = lint_mod.format_github(result)
        if out:
            click.echo(out)
    else:
        click.echo(lint_mod.format_text(result))
    if result.findings:
        raise SystemExit(1)


@cli.group()
def ckpt():
    """Distributed checkpoint inspection (ray_tpu.checkpoint)."""


def _resolve_run_dir(run, storage_path):
    run_dir = run if storage_path is None else os.path.join(storage_path,
                                                            run)
    if not os.path.isdir(run_dir):
        raise click.ClickException(
            f"no run directory at {run_dir} — pass the "
            f"<storage>/<experiment> path, or --storage-path plus the "
            f"experiment name")
    return run_dir


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


@ckpt.command("ls")
@click.argument("run")
@click.option("--storage-path", default=None,
              help="Prepend to RUN (otherwise RUN is the run dir path).")
@click.option("--deep", is_flag=True,
              help="Verify shard crc32s, not just manifest + sizes.")
def ckpt_ls(run, storage_path, deep):
    """List a run's checkpoints: step, size, shards, replica presence,
    and validity (manifest self-checksum + shard verification).
    Uncommitted directories (in-flight or crashed saves) show as
    ``uncommitted`` — they are invisible to restore by design."""
    from ray_tpu.checkpoint import scan_run_dir
    recs = scan_run_dir(_resolve_run_dir(run, storage_path), deep=deep)
    if not recs:
        click.echo("no checkpoints")
        return
    click.echo(f"{'STEP':>8}  {'SIZE':>10}  {'SHARDS':>6}  "
               f"{'REPLICA':>7}  STATUS")
    bad = 0
    for r in recs:
        if not r["committed"]:
            status = "uncommitted"
        elif r["valid"]:
            status = "valid"
        else:
            status = "INVALID: " + "; ".join(r["problems"])
            bad += 1
        click.echo(f"{r['step']:>8}  {_fmt_bytes(r.get('bytes', 0)):>10}  "
                   f"{r.get('shards', 0):>6}  "
                   f"{'yes' if r.get('replica') else 'no':>7}  {status}")
    if bad:
        raise SystemExit(1)


@ckpt.command("inspect")
@click.argument("run")
@click.option("--storage-path", default=None)
@click.option("--step", type=int, default=None,
              help="Checkpoint step (default: newest committed).")
@click.option("--deep", is_flag=True, help="Re-read shards and check crcs.")
def ckpt_inspect(run, storage_path, step, deep):
    """Print one checkpoint's manifest: leaves, shard map, validity."""
    from ray_tpu.checkpoint import read_manifest, scan_run_dir, \
        verify_checkpoint
    run_dir = _resolve_run_dir(run, storage_path)
    recs = [r for r in scan_run_dir(run_dir) if r["committed"]]
    if step is not None:
        recs = [r for r in recs if r["step"] == step]
    if not recs:
        raise click.ClickException(
            "no committed checkpoint" +
            (f" at step {step}" if step is not None else ""))
    rec = recs[-1]
    problems = verify_checkpoint(rec["path"], deep=deep)
    try:
        manifest = read_manifest(rec["path"])
    except Exception as e:
        # Inspect exists to diagnose exactly this checkpoint: a corrupt
        # manifest is a report, not a traceback.
        click.echo(f"path:      {rec['path']}")
        click.echo(f"step:      {rec['step']}")
        click.echo(f"valid:     {'; '.join(problems) or e}")
        raise SystemExit(1)
    click.echo(f"path:      {rec['path']}")
    click.echo(f"step:      {manifest['step']}")
    click.echo(f"world:     {manifest['world_size']} "
               f"({len(manifest['shards'])} shards, "
               f"{_fmt_bytes(manifest['total_bytes'])})")
    click.echo(f"replica:   {'yes' if manifest['replica'] else 'no'}")
    click.echo(f"valid:     "
               f"{'yes' if not problems else '; '.join(problems)}")
    if manifest.get("metrics"):
        click.echo(f"metrics:   {json.dumps(manifest['metrics'])}")
    click.echo("leaves:")
    for key, spec in sorted(manifest["leaves"].items()):
        shape = "x".join(str(d) for d in spec["global_shape"]) or "scalar"
        click.echo(f"  {key}  {spec['dtype']}[{shape}]")
    if problems:
        raise SystemExit(1)


@cli.command()
@click.option("--address", default=None)
@click.option("--deadline-s", type=float, default=30.0, show_default=True,
              help="Seconds until the node is expected to die; train/"
                   "serve controllers must evacuate within this window.")
@click.option("--reason", default="manual", show_default=True)
@click.option("--undrain", is_flag=True,
              help="Cancel a drain instead of starting one.")
@click.argument("node")
def drain(address, deadline_s, reason, undrain, node):
    """Start a graceful drain of NODE (node id hex, prefix ok): it stops
    taking new leases, training checkpoints urgently and re-forms
    without it, serve replaces its replicas — all before the deadline.
    This is the manual twin of the cloud preemption-notice hook."""
    from urllib.parse import urlencode
    client = _client(address)
    # Prefix resolution: operators paste the 12-char id `status` prints.
    nodes = client.cluster_status()["nodes"]
    matches = [n for n in nodes if n["node_id"].startswith(node)
               and n["alive"]]
    if not matches:
        raise click.ClickException(f"no alive node matching {node!r}")
    if len(matches) > 1:
        raise click.ClickException(
            f"ambiguous node prefix {node!r}: "
            + ", ".join(n["node_id"][:12] for n in matches))
    node_id = matches[0]["node_id"]
    q = {"node_id": node_id, "deadline_s": deadline_s, "reason": reason}
    if undrain:
        q["undrain"] = "1"
    client._request("POST", "/api/cluster/drain_node?" + urlencode(q))
    if undrain:
        click.echo(f"node {node_id[:12]} undrained")
    else:
        click.echo(f"node {node_id[:12]} draining "
                   f"(deadline {deadline_s:g}s, reason {reason})")


@cli.command()
@click.option("--address", default=None)
@click.option("--duration-s", type=float, default=2.0, show_default=True,
              help="How long every process samples its threads.")
@click.option("--hz", type=float, default=67.0, show_default=True,
              help="Host sampling rate.")
@click.option("--jax", "jax_profile", is_flag=True,
              help="Also bracket the window with jax.profiler on every "
                   "worker that has jax loaded (TensorBoard artifacts "
                   "land under <session>/profiles/<id>/jax/).")
@click.option("--output", "-o", default="profile_trace.json",
              show_default=True,
              help="Write the merged Chrome-trace JSON here (load in "
                   "chrome://tracing or https://ui.perfetto.dev).")
def profile(address, duration_s, hz, jax_profile, output):
    """Capture a cluster-wide performance profile: every live worker
    (plus the driver) samples for the duration, and the head merges the
    records into ONE clock-aligned Chrome trace — the first thing to run
    when step time regresses and the stack dump looks healthy."""
    from urllib.parse import urlencode
    client = _client(address)
    q = {"duration_s": duration_s, "hz": hz}
    if jax_profile:
        q["jax"] = "1"
    out = client._request("POST",
                          "/api/cluster/profile?" + urlencode(q))
    trace = out.pop("trace", None)
    if trace is not None:
        with open(output, "w") as f:
            json.dump(trace, f)
        click.echo(f"wrote {len(trace.get('traceEvents', []))} events "
                   f"to {output}")
    click.echo(f"head copy: {out.get('path')}")
    click.echo(f"workers captured: {len(out.get('workers', []))}")
    if out.get("unresponsive"):
        click.echo("unresponsive (no capture in time): "
                   + ", ".join(w[:12] for w in out["unresponsive"]))
        raise SystemExit(1)


@cli.group()
def debug():
    """Failure forensics (flight recorder)."""


@debug.command("dump")
@click.option("--address", default=None)
@click.option("--reason", default="manual", show_default=True)
def debug_dump(address, reason):
    """Write a postmortem bundle on the head — captured stacks, the task
    event tail, export events, a metrics snapshot, and the goodput
    breakdown — under <session>/debug/, and print the bundle path."""
    from urllib.parse import quote
    client = _client(address)
    out = client._request(
        "POST", f"/api/cluster/debug_dump?reason={quote(reason, safe='')}")
    click.echo(out["path"])


def main():
    cli()


if __name__ == "__main__":
    main()
