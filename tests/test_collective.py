"""Collective group tests: KV backend (pure python) + XLA-gloo backend
(2 worker processes, each its own jax CPU world member).

Mirrors the reference's CPU collective tests (reference:
python/ray/util/collective/tests/single_node_cpu_tests/,
distributed_cpu_tests/test_distributed_allreduce.py)."""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote
class KVCollectiveWorker:
    def __init__(self, rank, world):
        self.rank, self.world = rank, world

    def setup(self, group):
        from ray_tpu import collective as col
        col.init_collective_group(self.world, self.rank, backend="kv",
                                  group_name=group)
        return True

    def run_ops(self, group):
        from ray_tpu import collective as col
        out = {}
        x = np.full(4, float(self.rank + 1), np.float32)
        out["allreduce"] = col.allreduce(x, group)
        out["allgather"] = col.allgather(
            np.array([self.rank], np.float32), group)
        out["broadcast"] = col.broadcast(
            np.full(2, float(self.rank), np.float32), src_rank=1,
            group_name=group)
        rs_in = np.arange(self.world * 2, dtype=np.float32)
        out["reducescatter"] = col.reducescatter(rs_in, group)
        col.barrier(group)
        out["rank"] = col.get_rank(group)
        return out

    def p2p(self, group):
        from ray_tpu import collective as col
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=group)
            return None
        return col.recv((1,), np.float32, src_rank=0, group_name=group)


class TestKVBackend:
    def test_all_ops(self, ray_start):
        world = 3
        workers = [KVCollectiveWorker.remote(r, world) for r in range(world)]
        assert all(ray_tpu.get(
            [w.setup.remote("g1") for w in workers], timeout=60))
        results = ray_tpu.get(
            [w.run_ops.remote("g1") for w in workers], timeout=60)
        for r, res in enumerate(results):
            np.testing.assert_allclose(res["allreduce"], np.full(4, 6.0))
            np.testing.assert_allclose(res["allgather"], [[0], [1], [2]])
            np.testing.assert_allclose(res["broadcast"], [1.0, 1.0])
            np.testing.assert_allclose(
                res["reducescatter"],
                3 * np.arange(world * 2, dtype=np.float32)[r * 2:(r + 1) * 2])
            assert res["rank"] == r

    def test_p2p(self, ray_start):
        workers = [KVCollectiveWorker.remote(r, 2) for r in range(2)]
        try:
            ray_tpu.get([w.setup.remote("g2") for w in workers], timeout=120)
            out = ray_tpu.get([w.p2p.remote("g2") for w in workers],
                              timeout=120)
        except Exception:
            # Rare full-suite-only flake under investigation: dump the
            # control-plane state so the next occurrence is actionable.
            rt = ray_start
            print("DIAG actors:", rt.ctl_list_actors())
            print("DIAG kv:", rt.ctl_kv_keys("collective/"))
            print("DIAG tasks:", rt.ctl_summarize_tasks())
            print("DIAG pending:", rt.scheduler.num_pending())
            raise
        np.testing.assert_allclose(out[1], [42.0])


@ray_tpu.remote
class XlaCollectiveWorker:
    """Each worker is a separate process with its own 1-device jax CPU
    runtime; the group forms a 2-process gloo world."""

    def __init__(self, rank, world):
        self.rank, self.world = rank, world

    def setup_and_allreduce(self, group):
        from ray_tpu import collective as col
        col.init_collective_group(self.world, self.rank, backend="xla",
                                  group_name=group)
        grad = np.full((8,), float(self.rank + 1), np.float32)
        reduced = col.allreduce(grad, group)
        gathered = col.allgather(np.array([self.rank], np.int32), group)
        col.barrier(group)
        return reduced, gathered


class TestXlaBackend:
    def test_two_process_gloo_allreduce(self, ray_start):
        world = 2
        env = {"env_vars": {"JAX_PLATFORMS": "cpu",
                            "PALLAS_AXON_POOL_IPS": "",
                            "XLA_FLAGS": ""}}
        workers = [
            XlaCollectiveWorker.options(runtime_env=env).remote(r, world)
            for r in range(world)]
        results = ray_tpu.get(
            [w.setup_and_allreduce.remote("xg1") for w in workers],
            timeout=180)
        for reduced, gathered in results:
            np.testing.assert_allclose(reduced, np.full((8,), 3.0))
            np.testing.assert_allclose(np.asarray(gathered).ravel(), [0, 1])
