"""Paged KV cache: fixed page pool + per-sequence block tables.

Reference analog: the vLLM engine the reference wraps (reference:
python/ray/llm/_internal/serve/engines/vllm/ — PagedAttention block
manager); here the cache is a functional JAX structure laid out for the
TPU paged-attention kernel (jax.experimental.pallas.ops.tpu.paged_attention
reads kv_pages [total_pages, page_size, 2 * num_kv_heads, head_dim]):

    kv_pages    : per-layer tuple of combined [NUM_PAGES, PAGE, 2*Hkv, D]
                  arrays (K even / V odd combined-head indices — see
                  _model.decode_step's layout note)
    block table : [max_slots, pages_per_seq] int32 page ids

Page allocation is host-side (free list in the engine); device arrays are
donated through the jitted step so decode updates are in-place.
"""

from __future__ import annotations

from typing import List, Optional


class PagePool:
    """Host-side page allocator (free list).  Page 0 is reserved as the
    null page so block tables can always point somewhere valid."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p != 0:
                self._free.append(p)

    @property
    def num_free(self) -> int:
        return len(self._free)
