"""Index algebra for sharded checkpoints.

An *index* is the slice of a global array one chunk covers, normalized to
``((start, stop), ...)`` with one pair per dimension.  The restore path
(``format._assemble``) intersects stored-chunk indexes with the requested
placement and copies overlapping regions; these helpers keep that logic
pure, boring and separately testable.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

Index = Tuple[Tuple[int, int], ...]


def full_index(shape: Sequence[int]) -> Index:
    return tuple((0, int(d)) for d in shape)


def normalize_index(index: Any, global_shape: Sequence[int]) -> Index:
    """Accepts None (full), slices, (start, stop) pairs, or lists thereof."""
    if index is None:
        return full_index(global_shape)
    out = []
    for i, d in enumerate(global_shape):
        p = index[i] if i < len(index) else None
        if p is None:
            out.append((0, int(d)))
        elif isinstance(p, slice):
            start, stop, stride = p.indices(int(d))
            if stride != 1:
                raise ValueError(f"strided shard index unsupported: {p}")
            out.append((start, stop))
        else:
            start, stop = p
            out.append((int(start), int(stop)))
    return tuple(out)


def index_from_slices(slices: Sequence[slice],
                      global_shape: Sequence[int]) -> Index:
    """jax ``Shard.index`` (tuple of slices) -> normalized index."""
    return normalize_index(tuple(slices), global_shape)


def index_shape(index: Index) -> Tuple[int, ...]:
    return tuple(stop - start for start, stop in index)


def index_size(index: Index) -> int:
    n = 1
    for start, stop in index:
        n *= max(0, stop - start)
    return n


def intersect(a: Index, b: Index) -> Optional[Index]:
    """Overlapping region of two indexes, or None when disjoint/empty."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def copy_region(dst, dst_index: Index, src, src_index: Optional[Index],
                region: Index, fill: bool = False) -> None:
    """Copy ``region`` (global coordinates) from ``src`` (covering
    ``src_index``) into ``dst`` (covering ``dst_index``).  With
    ``fill=True``, set the region to True instead (coverage masks)."""
    dst_sel = tuple(slice(lo - d0, hi - d0)
                    for (lo, hi), (d0, _) in zip(region, dst_index))
    if fill:
        dst[dst_sel] = True
        return
    src_sel = tuple(slice(lo - s0, hi - s0)
                    for (lo, hi), (s0, _) in zip(region, src_index))
    dst[dst_sel] = src[src_sel]


def even_shard(global_shape: Sequence[int], axis: int, rank: int,
               world: int) -> Index:
    """Rank ``rank``'s contiguous block of ``axis`` split ``world`` ways
    (remainder spread over the leading ranks, torch-DistributedSampler
    style)."""
    dim = int(global_shape[axis])
    base, rem = divmod(dim, world)
    start = rank * base + min(rank, rem)
    stop = start + base + (1 if rank < rem else 0)
    out = list(full_index(global_shape))
    out[axis] = (start, stop)
    return tuple(out)


def even_shard_spec(axis: int, rank: int, world: int) -> Callable:
    """``shard_spec`` for ``snapshot_tree``: every array leaf is this
    rank's even block of ``axis`` of a global array that is ``world``
    times larger along that axis.

    The local leaf on each rank is its OWN slice; the declared global
    shape scales the sharded axis back up.  Use with training loops where
    each rank materializes only its rows (e.g. optimizer state sharding).
    """
    def spec(key: str, leaf) -> Tuple[Tuple[int, ...], Index]:
        local = tuple(int(d) for d in leaf.shape)
        if not local:
            # Scalars cannot shard; declare them replicated (full index).
            return local, full_index(local)
        dim = local[axis] * world
        global_shape = local[:axis] + (dim,) + local[axis + 1:]
        idx = even_shard(global_shape, axis, rank, world)
        if index_shape(idx) != local:
            raise ValueError(
                f"leaf {key!r}: local shape {local} is not rank {rank}'s "
                f"even block of global {global_shape}")
        return global_shape, idx
    return spec


def even_placement(axis: int, rank: int, world: int) -> Callable:
    """``placement`` for ``restore_tree``: fetch this rank's even block
    of ``axis`` (the resharding-restore dual of ``even_shard_spec``)."""
    def placement(key: str, global_shape: Sequence[int]) -> Optional[Index]:
        if not global_shape:
            return None
        return even_shard(global_shape, axis, rank, world)
    return placement
