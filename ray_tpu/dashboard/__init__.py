"""ray_tpu.dashboard — cluster observability HTTP surface.

Reference analog: python/ray/dashboard/ (head.py:49 DashboardHead + aiohttp
module system under dashboard/modules/ — node, state, metrics, job, event).
The reference splits head/agent processes and a React frontend; here one
aiohttp server on the head serves JSON APIs straight off the in-process
state feeds (events buffer, controller tables, scheduler, user metrics) plus
a minimal HTML overview — the data plumbing is the same, the surface is
deliberately lean.
"""

from .server import DashboardServer, start_dashboard

__all__ = ["DashboardServer", "start_dashboard"]
