"""Tuner + trial execution.

Reference analog: python/ray/tune/tuner.py:43 Tuner / tuner.fit:319 ->
TuneController (tune/execution/tune_controller.py:68).  Trials run as
runtime tasks with bounded concurrency; ``tune.report`` inside a trial
publishes intermediate metrics through the KV store and polls its stop
flag, so schedulers (ASHA/median) can kill laggards mid-flight.
"""

from __future__ import annotations

import pickle
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .schedulers import CONTINUE, STOP, FIFOScheduler
from .search import generate_variants


class TuneStopException(Exception):
    """Raised inside a trial when the scheduler stops it early."""


_trial_ctx: Optional[Dict[str, Any]] = None


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Dict[str, Any]] = None) -> None:
    """Report intermediate metrics from inside a trial; raises
    TuneStopException when the scheduler has stopped this trial.

    ``checkpoint`` (a picklable dict) is stored as the trial's latest
    checkpoint — PBT exploits clone it into restarted trials."""
    if _trial_ctx is None:
        raise RuntimeError("tune.report() called outside a tune trial")
    from .._private.api import _control
    _trial_ctx["seq"] += 1
    metrics = dict(metrics)
    # NTP-immune trial elapsed, injected where schedulers/result rows can
    # actually read it (reference: tune auto-fills time_total_s).  A user
    # metric of the same name wins.
    metrics.setdefault("time_total_s",
                       time.monotonic() - _trial_ctx["t0_mono"])
    if checkpoint is not None:
        _control("kv_put",
                 f"tune/{_trial_ctx['run_id']}/ckpt/"
                 f"{_trial_ctx['trial_id']}", pickle.dumps(checkpoint))
    _control("kv_put",
             f"tune/{_trial_ctx['run_id']}/report/{_trial_ctx['trial_id']}/"
             f"{_trial_ctx['seq']}",
             pickle.dumps({"metrics": metrics,
                           "seq": _trial_ctx["seq"],
                           "time": time.time()}))  # wall: display only
    stop = _control(
        "kv_get", f"tune/{_trial_ctx['run_id']}/stop/"
                  f"{_trial_ctx['trial_id']}")
    if stop is not None:
        raise TuneStopException()


def get_checkpoint() -> Optional[Dict[str, Any]]:
    """Inside a trial: the checkpoint this trial was (re)started from
    (PBT exploit), or None for a fresh start."""
    if _trial_ctx is None:
        raise RuntimeError("tune.get_checkpoint() outside a tune trial")
    return _trial_ctx.get("initial_checkpoint")


def _run_trial(fn_blob: bytes, config: Dict[str, Any], run_id: str,
               trial_id: str, ckpt_blob: Optional[bytes] = None):
    global _trial_ctx
    from .._private import serialization
    fn = serialization.loads_control(fn_blob)
    _trial_ctx = {"run_id": run_id, "trial_id": trial_id, "seq": 0,
                  "t0_mono": time.monotonic(),
                  "initial_checkpoint":
                      pickle.loads(ckpt_blob) if ckpt_blob else None}
    try:
        out = fn(config)
        return {"final": out if isinstance(out, dict) else {},
                "stopped": False}
    except TuneStopException:
        return {"final": {}, "stopped": True}
    finally:
        _trial_ctx = None


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    # Adaptive search algorithm (reference: tune_config.search_alg — e.g.
    # TPESearcher / ConcurrencyLimiter).  None = grid/random variants from
    # param_space.  With a search_alg, num_samples is the TOTAL number of
    # trials and param_space is owned by the searcher.
    search_alg: Any = None
    seed: int = 0


@dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    error: Optional[str] = None
    stopped_early: bool = False
    history: List[Dict[str, Any]] = field(default_factory=list)
    restarts: int = 0  # PBT exploit relaunches


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        pick = min if mode == "min" else max
        return pick(scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, **{f"config/{k}": v
                                              for k, v in r.config.items()}}
            row.update(r.metrics)
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    """reference: tune/tuner.py:43 — trainable is a function taking a
    config dict (function-trainable API)."""

    def __init__(self, trainable: Callable,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._cfg = tune_config or TuneConfig()

    def fit(self) -> ResultGrid:
        import ray_tpu
        from .._private import serialization
        from .._private.api import _control

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        run_id = uuid.uuid4().hex[:12]
        scheduler = self._cfg.scheduler or FIFOScheduler()
        search_alg = self._cfg.search_alg
        fn_blob = serialization.dumps_control(self._trainable)
        run_remote = ray_tpu.remote(_run_trial)

        trials: Dict[str, Dict[str, Any]] = {}
        queue = []

        def _new_trial(cfg: Dict[str, Any]) -> str:
            tid = uuid.uuid4().hex[:8]
            trials[tid] = {"config": cfg, "ref": None, "history": [],
                           "seen": set(), "ckpt_blob": None, "restarts": 0,
                           "kv_tid": tid}
            queue.append(tid)
            if hasattr(scheduler, "register_trial"):
                scheduler.register_trial(tid, cfg)
            return tid

        if search_alg is None:
            for cfg in generate_variants(self._param_space,
                                         self._cfg.num_samples,
                                         self._cfg.seed):
                _new_trial(cfg)
        suggested = 0

        in_flight: Dict[Any, str] = {}
        results: List[TrialResult] = []

        def poll_reports():
            for key in _control("kv_keys", f"tune/{run_id}/report/"):
                parts = key.split("/")
                kv_tid, seq = parts[-2], int(parts[-1])
                # kv ids are generation-namespaced (tid.g<N> after a PBT
                # restart) so a relaunched trial's seqs can't collide with
                # its previous incarnation's.
                tid = kv_tid.split(".g")[0]
                t = trials.get(tid)
                if t is None or t["kv_tid"] != kv_tid \
                        or (kv_tid, seq) in t["seen"]:
                    continue
                t["seen"].add((kv_tid, seq))
                payload = pickle.loads(_control("kv_get", key))
                t["history"].append(payload["metrics"])
                metric_val = payload["metrics"].get(self._cfg.metric)
                if metric_val is not None:
                    decision = scheduler.on_result(tid, seq,
                                                   float(metric_val))
                    if decision == STOP:
                        _control("kv_put",
                                 f"tune/{run_id}/stop/{kv_tid}", b"1")

        def _searcher_refill():
            """Ask the search algorithm for more trials (suggest-driven
            mode; reference: SearchGenerator feeding TuneController)."""
            nonlocal suggested
            while suggested < self._cfg.num_samples and \
                    len(in_flight) + len(queue) < \
                    self._cfg.max_concurrent_trials:
                tid = uuid.uuid4().hex[:8]
                cfg = search_alg.suggest(tid)
                if cfg is None:
                    break  # limiter saturated or space exhausted
                trials[tid] = {"config": cfg, "ref": None, "history": [],
                               "seen": set(), "ckpt_blob": None,
                               "restarts": 0, "kv_tid": tid}
                # NOTE: tid is pre-chosen so the searcher sees the same id
                # the tuner reports completion with.
                queue.append(tid)
                if hasattr(scheduler, "register_trial"):
                    scheduler.register_trial(tid, cfg)
                suggested += 1

        if search_alg is not None:
            _searcher_refill()
        while queue or in_flight or (
                search_alg is not None
                and suggested < self._cfg.num_samples):
            if search_alg is not None:
                _searcher_refill()
                if not queue and not in_flight:
                    # Limiter blocked with nothing running: cannot progress.
                    break
            while queue and len(in_flight) < self._cfg.max_concurrent_trials:
                tid = queue.pop(0)
                ref = run_remote.options(
                    name=f"trial-{tid}").remote(
                        fn_blob, trials[tid]["config"], run_id,
                        trials[tid]["kv_tid"], trials[tid]["ckpt_blob"])
                trials[tid]["ref"] = ref
                in_flight[ref] = tid
            done, _ = ray_tpu.wait(list(in_flight.keys()), num_returns=1,
                                   timeout=0.2)
            poll_reports()
            for ref in done:
                tid = in_flight.pop(ref)
                t = trials[tid]
                error = None
                stopped = False
                final: Dict[str, Any] = {}
                try:
                    out = ray_tpu.get(ref)
                    final = out["final"]
                    stopped = out["stopped"]
                except Exception as e:  # noqa: BLE001
                    error = repr(e)
                # PBT exploit: the stop was a pause — relaunch the trial
                # with the mutated config seeded from a top performer's
                # checkpoint (reference: pbt.py exploit/explore cycle).
                restart = None
                if hasattr(scheduler, "take_restart"):
                    # Always drain the directive: a STOP landing on the
                    # trial's final report leaves one behind, which must
                    # not leak (the trial completed anyway).
                    restart = scheduler.take_restart(tid)
                _control("kv_del", f"tune/{run_id}/stop/{t['kv_tid']}")
                if stopped and restart is not None and t["restarts"] < 16:
                    new_config, source = restart
                    t["config"] = new_config
                    t["restarts"] += 1
                    t["kv_tid"] = f"{tid}.g{t['restarts']}"
                    src_kv = trials[source]["kv_tid"] \
                        if source in trials else source
                    t["ckpt_blob"] = _control(
                        "kv_get", f"tune/{run_id}/ckpt/{src_kv}") or \
                        _control("kv_get", f"tune/{run_id}/ckpt/{source}")
                    if hasattr(scheduler, "register_trial"):
                        scheduler.register_trial(tid, new_config)
                    queue.append(tid)
                    continue
                last = t["history"][-1] if t["history"] else {}
                metrics = {**last, **final}
                if search_alg is not None:
                    # Searchers minimize; flip for mode="max".
                    val = metrics.get(self._cfg.metric)
                    score = None
                    if val is not None:
                        score = float(val) if self._cfg.mode == "min" \
                            else -float(val)
                    search_alg.on_trial_complete(tid, score)
                results.append(TrialResult(
                    tid, t["config"], metrics, error, stopped,
                    t["history"], restarts=t["restarts"]))
        poll_reports()
        return ResultGrid(results, self._cfg.metric, self._cfg.mode)
