"""Merge per-process capture records into one Chrome-trace/Perfetto JSON.

Reference: python/ray/_private/state.py:471 (chrome_tracing_dump) — same
output dialect (trace-event JSON, ``ph: X`` complete events + ``ph: M``
metadata), loadable in chrome://tracing, Perfetto and speedscope.

Every record's events are shifted by its ``clock_offset_s`` so the whole
trace sits on the DRIVER's clock: a slice at t on worker A and a slice
at t on worker B happened at the same driver-observed instant, which is
what makes cross-worker straggler analysis readable.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional


def _slices_for_record(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Fold a record's stack samples into trace slices: consecutive
    samples of one thread with the same leaf frame coalesce into one
    ``X`` event named by that leaf (a poor man's flame timeline)."""
    events: List[Dict[str, Any]] = []
    offset = rec.get("clock_offset_s") or 0.0
    period = 1.0 / max(1.0, rec.get("hz") or 67.0)
    who = "driver" if rec.get("is_driver") \
        else f"worker:{(rec.get('worker_id') or '?')[:8]}"
    pid = f"{who} pid={rec.get('pid')}"
    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "tid": 0, "args": {"name": pid}})
    # thread ident -> (leaf, start_wall, last_wall, stack, name)
    open_slices: Dict[int, List[Any]] = {}

    def close(tid: int) -> None:
        leaf, start, last, stack, name = open_slices.pop(tid)
        events.append({
            "name": leaf, "cat": "sample", "ph": "X",
            "ts": (start - offset) * 1e6,
            "dur": max(period, last - start + period) * 1e6,
            "pid": pid, "tid": f"{name} ({tid})",
            "args": {"stack": stack},
        })

    for sample in rec.get("samples", ()):
        t = sample["t"]
        threads = sample.get("threads", {})
        for tid in list(open_slices):
            cur = open_slices[tid]
            new = threads.get(tid)
            # A gap (thread died / sampler stalled) or a leaf change
            # closes the slice.
            if new is None or new["leaf"] != cur[0] \
                    or t - cur[2] > 4 * period:
                close(tid)
        for tid, th in threads.items():
            if tid in open_slices:
                open_slices[tid][2] = t
            else:
                open_slices[tid] = [th["leaf"], t, t,
                                    list(th.get("stack", ())),
                                    th.get("name", f"t{tid}")]
    for tid in list(open_slices):
        close(tid)
    return events


def merge_records(records: List[Dict[str, Any]],
                  timeline_events: Optional[List[Dict[str, Any]]] = None,
                  window: Optional[tuple] = None,
                  meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build the merged Chrome-trace document.

    ``records`` are capture_profile outputs (driver + workers);
    ``timeline_events`` are the driver's existing chrome_trace events
    (profile spans, task slices) — filtered to ``window`` (wall seconds,
    driver clock) so the on-demand capture carries the framework's own
    span context for the same interval.
    """
    events: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    for rec in records:
        if rec.get("error"):
            processes.append({"worker_id": rec.get("worker_id"),
                              "pid": rec.get("pid"),
                              "error": rec["error"]})
            continue
        events.extend(_slices_for_record(rec))
        processes.append({
            "worker_id": rec.get("worker_id"),
            "pid": rec.get("pid"),
            "is_driver": bool(rec.get("is_driver")),
            "clock_offset_s": rec.get("clock_offset_s"),
            "num_samples": len(rec.get("samples", ())),
            "jax_profile": {
                "attempted": rec.get("jax_profile", {}).get("attempted"),
                "num_files": len(rec.get("jax_profile", {})
                                 .get("files", {})),
                "error": rec.get("jax_profile", {}).get("error"),
            },
            "memory": rec.get("memory", []),
        })
    if timeline_events:
        lo = (window[0] * 1e6) if window else None
        hi = (window[1] * 1e6) if window else None
        for ev in timeline_events:
            ts = ev.get("ts")
            if ts is None:
                continue
            if lo is not None and (ts + ev.get("dur", 0.0) < lo
                                   or ts > hi):
                continue
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": dict(meta or {}, processes=processes),
    }


def write_trace(path: str, doc: Dict[str, Any]) -> str:
    """Publish the merged trace atomically (tmp + rename: a reader —
    the dashboard, a human mid-download — never sees a torn file)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def write_jax_artifacts(profile_dir: str,
                        records: List[Dict[str, Any]]) -> List[str]:
    """Land each record's shipped jax.profiler artifact files under
    ``<profile_dir>/jax/<worker8>/``; returns the written paths."""
    written: List[str] = []
    for rec in records:
        files = (rec.get("jax_profile") or {}).get("files") or {}
        if not files:
            continue
        who = (rec.get("worker_id") or "proc")[:8]
        for rel, blob in files.items():
            # The artifact relpaths come from the profiled process's own
            # tempdir walk, but normalize defensively anyway.
            rel = os.path.normpath(rel).lstrip(os.sep)
            if rel.startswith(".."):
                continue
            dest = os.path.join(profile_dir, "jax", who, rel)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            tmp = dest + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, dest)
            written.append(dest)
    return written
