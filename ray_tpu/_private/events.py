"""Task lifecycle event buffer feeding the state API and the timeline.

Reference: src/ray/core_worker/task_event_buffer.h:304 (TaskEventBuffer
batching task state transitions to the GCS) + src/ray/gcs/gcs_task_manager.h:97
(bounded task-event history served to the dashboard/state API) +
profile events (src/ray/core_worker/profile_event.h) that become the
``ray timeline`` chrome trace (python/ray/_private/state.py:471
chrome_tracing_dump).

Single-process control plane → one bounded buffer on the driver runtime; the
worker side reports through the existing TaskDone/note_task_running paths so
no extra RPC is needed.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..schedview.decisions import enabled as _sched_trace_enabled

# Task states, mirroring the reference's TaskStatus enum (common.proto),
# plus the two scheduler-internal stages the schedview lifecycle
# attribution adds (deps resolved -> ready queue; placement booked).
PENDING_ARGS = "PENDING_ARGS_AVAIL"
READY = "READY"
PLACED = "PLACED"
SUBMITTED_TO_NODE = "SUBMITTED_TO_WORKER"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"

# Stage-wait label per ARRIVING state: the wait is monotonic-minus-
# monotonic against the previous recorded transition of the same task
# (never wall-clock arithmetic — the RT203 class), published as
# ray_tpu_sched_stage_wait_seconds{stage=...}.
_STAGE_LABEL = {
    READY: "deps",               # submit -> deps resolved / ready
    PLACED: "queue",             # ready -> placement booked
    SUBMITTED_TO_NODE: "dispatch",  # placed -> shipped to a node
    RUNNING: "startup",          # dispatched -> executing
    FINISHED: "run",             # running -> done
    FAILED: "run",
}


@dataclass
class TaskEvent:
    task_id: str
    name: str
    state: str = PENDING_ARGS
    type: str = "NORMAL_TASK"  # NORMAL_TASK | ACTOR_CREATION_TASK | ACTOR_TASK
    actor_id: Optional[str] = None
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    error_message: Optional[str] = None
    # state -> unix seconds of first entry into that state
    state_times: Dict[str, float] = field(default_factory=dict)
    # stage label -> seconds waited entering that stage (monotonic
    # deltas folded from the per-record mono stamps; see _STAGE_LABEL)
    stage_waits: Dict[str, float] = field(default_factory=dict)
    # Monotonic stamp of the last folded transition (not serialized).
    last_mono: Optional[float] = field(default=None, repr=False)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "task_id": self.task_id, "name": self.name, "state": self.state,
            "type": self.type, "actor_id": self.actor_id,
            "node_id": self.node_id, "worker_id": self.worker_id,
            "error_message": self.error_message,
            "state_times": dict(self.state_times),
            "stage_waits": dict(self.stage_waits),
        }


@dataclass
class ProfileSpan:
    """A user/system span for the chrome-trace timeline."""
    name: str
    category: str
    start_s: float
    end_s: float
    pid: str  # row group (node / component)
    tid: str  # row (worker / thread)
    extra: Optional[Dict[str, Any]] = None


class TaskEventBuffer:
    """Bounded, insertion-ordered task event history (oldest evicted).

    ``record`` is on the per-task dispatch path (4 transitions per task),
    so it only appends a tuple to a deque — folding transitions into
    per-task TaskEvent state happens lazily at read time (reference:
    task_event_buffer.h batches transitions and ships them OFF the task
    path for the same reason)."""

    def __init__(self, max_events: int = 10000):
        self._max = max_events
        self._events: "OrderedDict[str, TaskEvent]" = OrderedDict()
        self._spans: List[ProfileSpan] = []
        self._lock = threading.Lock()
        self.num_dropped = 0
        from collections import deque
        self._pending: "deque" = deque()
        self._fold_at = max(1000, min(max_events * 2, 100_000))

    def record(self, task_id: str, state: str, *, name: Optional[str] = None,
               task_type: Optional[str] = None, actor_id: Optional[str] = None,
               node_id: Optional[str] = None, worker_id: Optional[str] = None,
               error_message: Optional[str] = None) -> None:
        # deque.append is thread-safe; no lock on the hot path.  ONE
        # clock read: records carry the monotonic stamp (stage waits
        # are mono-minus-mono, so an NTP step between two transitions
        # can never mint a negative/garbage latency) and the fold maps
        # mono->wall through a per-batch offset for state_times.
        # Safe bare access: deque.append is thread-safe by design (the
        # documented lock-free hot path above); _lock only guards folds.
        self._pending.append((task_id, state,  # ray-tpu: noqa[RT401]
                              time.monotonic(),
                              name, task_type, actor_id, node_id, worker_id,
                              error_message))
        if len(self._pending) >= self._fold_at:
            self._fold()

    def _fold(self) -> None:
        waits: list = []
        # Stage waits are only derived while tracing is on: with the
        # scheduler's READY/PLACED stamps disabled, the delta into
        # SUBMITTED would silently absorb queue+deps wait and point an
        # operator at dispatch when the bottleneck was placement.
        trace = _sched_trace_enabled()
        # Mono->wall basis shift for this batch's display stamps, not
        # an interval.
        wall_offset = time.time() - time.monotonic()  # ray-tpu: noqa[RT203]
        with self._lock:
            while True:
                try:
                    (task_id, state, mono, name, task_type, actor_id,
                     node_id, worker_id, error_message) = \
                        self._pending.popleft()
                except IndexError:
                    break
                now = mono + wall_offset
                ev = self._events.get(task_id)
                if ev is None:
                    ev = TaskEvent(task_id=task_id, name=name or "")
                    self._events[task_id] = ev
                    if len(self._events) > self._max:
                        self._events.popitem(last=False)
                        self.num_dropped += 1
                if name:
                    ev.name = name
                if task_type:
                    ev.type = task_type
                if actor_id:
                    ev.actor_id = actor_id
                if node_id:
                    ev.node_id = node_id
                if worker_id:
                    ev.worker_id = worker_id
                if error_message is not None:
                    ev.error_message = error_message
                ev.state = state
                ev.state_times.setdefault(state, now)
                if trace:
                    stage = _STAGE_LABEL.get(state)
                    if stage is not None and ev.last_mono is not None:
                        dt = max(0.0, mono - ev.last_mono)
                        ev.stage_waits[stage] = \
                            ev.stage_waits.get(stage, 0.0) + dt
                        waits.append((stage, dt))
                ev.last_mono = mono
        # Histogram publication happens OUTSIDE the buffer lock (the
        # metrics registry has its own) and BATCHED per stage — one
        # tag-key/lock cycle per fold, not five per task.  Gated by the
        # same switch as the decision ring so the control_plane bench's
        # off/on overhead reps toggle the whole addition.
        if waits:
            from ray_tpu.util import telemetry
            by_stage: Dict[str, list] = {}
            for stage, dt in waits:
                by_stage.setdefault(stage, []).append(dt)
            for stage, vals in by_stage.items():
                telemetry.observe_many("ray_tpu_sched_stage_wait_seconds",
                                       vals, tags={"stage": stage})

    def add_span(self, span: ProfileSpan) -> None:
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self._max:
                self._spans = self._spans[-self._max:]

    def snapshot(self, filters: Optional[Dict[str, Any]] = None,
                 limit: int = 10000, stage: Optional[str] = None,
                 min_stage_wait_s: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """Filtered task records, newest-``limit`` in insertion order.

        Filters are pushed below the dict materialization and the scan
        walks newest-first with an early exit, so a point lookup
        (``state.get_task``) touches O(limit) records even when the ring
        holds the 10k-node bench's full task table.  ``stage`` +
        ``min_stage_wait_s`` select tasks by lifecycle-stage latency
        (e.g. every task that waited >1s in ``queue``)."""
        if limit <= 0:
            return []
        self._fold()
        out: List[Dict[str, Any]] = []
        with self._lock:
            for ev in reversed(self._events.values()):
                if filters:
                    rec = ev.to_dict()
                    if any(rec.get(k) != v for k, v in filters.items()):
                        continue
                else:
                    rec = None
                if stage is not None:
                    wait = ev.stage_waits.get(stage)
                    if wait is None or (min_stage_wait_s is not None
                                        and wait < min_stage_wait_s):
                        continue
                out.append(rec if rec is not None else ev.to_dict())
                if len(out) >= limit:
                    break
        out.reverse()
        return out

    def summary(self, states: Optional[List[str]] = None,
                limit: Optional[int] = None) -> Dict[str, Dict[str, int]]:
        """name -> state -> count (reference: util/state summarize_tasks).

        ``states`` restricts to tasks currently in one of those states;
        ``limit`` caps the scan to the newest N records — both applied
        server-side so summaries stay cheap at bench scale."""
        self._fold()
        out: Dict[str, Dict[str, int]] = {}
        scanned = 0
        with self._lock:
            for ev in reversed(self._events.values()):
                if limit is not None and scanned >= limit:
                    break
                scanned += 1
                if states is not None and ev.state not in states:
                    continue
                per = out.setdefault(ev.name or "<unnamed>", {})
                per[ev.state] = per.get(ev.state, 0) + 1
        return out

    def find_ids(self, prefix: str, limit: int = 8) -> List[str]:
        """Task ids starting with ``prefix``, newest first (operators
        paste truncated ids into `ray-tpu task why`)."""
        self._fold()
        out: List[str] = []
        with self._lock:
            for tid in reversed(self._events):
                if tid.startswith(prefix):
                    out.append(tid)
                    if len(out) >= limit:
                        break
        return out

    def stats(self) -> Dict[str, int]:
        """Buffer health: ring saturation under load must be VISIBLE
        (a silently clipped history reads as 'no pending tasks').

        ``fold_backlog`` is sampled BEFORE the fold this read performs:
        it reports how many raw transitions had accumulated since the
        last fold (fold pressure), while ``num_events``/``num_dropped``
        are accurate post-fold."""
        backlog = len(self._pending)
        self._fold()
        with self._lock:
            return {"num_events": len(self._events),
                    "capacity": self._max,
                    "num_dropped": self.num_dropped,
                    "fold_backlog": backlog}

    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Chrome trace-event JSON (``ph: X`` complete events), one row per
        worker, one group per node — loadable in chrome://tracing and
        Perfetto (reference: _private/state.py:471 chrome_tracing_dump)."""
        trace: List[Dict[str, Any]] = []
        self._fold()
        with self._lock:
            events = list(self._events.values())
            spans = list(self._spans)
        for ev in events:
            start = ev.state_times.get(RUNNING)
            if start is None:
                continue
            end = (ev.state_times.get(FINISHED)
                   or ev.state_times.get(FAILED) or time.time())
            trace.append({
                "name": ev.name, "cat": "task", "ph": "X",
                "ts": start * 1e6, "dur": max(0.0, (end - start)) * 1e6,
                "pid": f"node:{(ev.node_id or 'driver')[:8]}",
                "tid": f"worker:{(ev.worker_id or '?')[:8]}",
                "args": {"task_id": ev.task_id, "state": ev.state},
            })
            # Queueing time as a lighter-weight slice.
            sub = ev.state_times.get(PENDING_ARGS)
            if sub is not None and start > sub:
                trace.append({
                    "name": f"{ev.name} (queued)", "cat": "scheduler",
                    "ph": "X", "ts": sub * 1e6, "dur": (start - sub) * 1e6,
                    "pid": "scheduler", "tid": "queue",
                    "args": {"task_id": ev.task_id},
                })
        for sp in spans:
            trace.append({
                "name": sp.name, "cat": sp.category, "ph": "X",
                "ts": sp.start_s * 1e6,
                "dur": max(0.0, sp.end_s - sp.start_s) * 1e6,
                "pid": sp.pid, "tid": sp.tid, "args": sp.extra or {},
            })
        return trace
