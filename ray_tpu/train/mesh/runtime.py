"""Worker/controller mesh runtime: build, observe, and place onto meshes.

Worker side: ``build_worker_mesh`` turns the resolved MeshSpec into a
``jax.sharding.Mesh`` over the GLOBAL device set of the jax.distributed
world the controller formed (installed as the ambient mesh so
``ops.ring_attention``/``parallel.pipeline`` find it), and the
``train.shard()`` helpers place params/batches onto it.

Controller side: ``publish_mesh_status`` drops the live mesh shape into
the head KV store so ``ray-tpu status`` (and the dashboard's
``/api/cluster/status``) show it without touching the training job.

Telemetry (all declared in util/telemetry.py CATALOG, RT204):
``ray_tpu_train_mesh_axis_size{axis}``, ``ray_tpu_train_param_shard_bytes``
and ``ray_tpu_train_mesh_reshapes_total``.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from ...util import telemetry

def xla_host_device_flags(flags: Optional[str], n: int) -> str:
    """XLA_FLAGS with ``--xla_force_host_platform_device_count`` pinned
    to ``n`` (any existing setting replaced) — the one spelling of the
    CPU multi-device recipe, shared by the controller's worker env and
    the bench's re-exec."""
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   flags or "")
    return (flags.strip()
            + f" --xla_force_host_platform_device_count={n}").strip()


#: KV key the controller publishes the live mesh shape under (the
#: ``ray-tpu status`` / cluster_status "mesh" section; same last-writer
#: pattern — and same ``diagnostics/`` namespace — as the watchdog's
#: VERDICT_KV_KEY.  NOT under ``train/``: that namespace is
#: consumed-and-deleted per run (RT303), while this record must outlive
#: the run so status shows the last known shape).
MESH_KV_KEY = "diagnostics/mesh/last"


def build_worker_mesh(spec, devices=None):
    """Build the global mesh for this worker's SPMD world, install it as
    the ambient mesh, and refresh the axis-size gauges."""
    from ...parallel.mesh import build_mesh, set_global_mesh
    mesh = build_mesh(spec, devices)
    set_global_mesh(mesh)
    note_mesh_axes(dict(zip(mesh.axis_names, mesh.devices.shape)))
    return mesh


def note_mesh_axes(axes: Dict[str, int]) -> None:
    for axis, size in axes.items():
        telemetry.set_gauge("ray_tpu_train_mesh_axis_size", float(size),
                            tags={"axis": axis})


def addressable_param_bytes(tree) -> int:
    """Bytes of ``tree`` this PROCESS holds: the sum over leaves of the
    distinct addressable shards' bytes (a sharded 7B on an 8-process
    fsdp8 mesh reports ~ total/8 per process)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += getattr(leaf, "nbytes", 0) or 0
            continue
        seen = set()
        for sh in shards:
            idx = tuple(
                (s.start, s.stop) for s in sh.index) if sh.index else ()
            if idx in seen:
                continue  # replicas of one shard count once
            seen.add(idx)
            total += sh.data.nbytes
    return total


def per_device_param_bytes(tree) -> Dict[str, int]:
    """Bytes of ``tree`` resident per addressable device — the
    shard-balance evidence the bench emits (max/device ~ total/N when
    parameters are truly sharded)."""
    import jax
    out: Dict[str, int] = {}
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for sh in shards:
            key = str(sh.device)
            out[key] = out.get(key, 0) + sh.data.nbytes
    return out


def note_param_shard_bytes(tree) -> int:
    n = addressable_param_bytes(tree)
    telemetry.set_gauge("ray_tpu_train_param_shard_bytes", float(n))
    return n


def publish_mesh_status(run_id: str, axes: Dict[str, int], world: int,
                        devices_per_worker: int) -> None:
    """Controller-side: record the live mesh shape in the head KV (best
    effort — status display must never fail a training run)."""
    from .reshape import mesh_descriptor
    try:
        from ..._private.api import _control
        _control("kv_put", MESH_KV_KEY, json.dumps({
            "run_id": run_id,
            "descriptor": mesh_descriptor(axes),
            "axes": {a: int(s) for a, s in axes.items()},
            "world": int(world),
            "devices_per_worker": int(devices_per_worker),
            "time": time.time(),
        }).encode())
    except Exception as e:  # noqa: BLE001 — observability is best-effort
        telemetry.note_swallowed("train.mesh.publish_status", e)


def read_mesh_status() -> Optional[Dict[str, Any]]:
    """The last published mesh shape (cluster_status / `ray-tpu status`)."""
    try:
        from ..._private.api import _control
        raw = _control("kv_get", MESH_KV_KEY)
        return json.loads(raw) if raw else None
    except Exception as e:  # noqa: BLE001
        telemetry.note_swallowed("train.mesh.read_status", e)
        return None


# -- data placement helpers (train.shard / train.shard_batch) ---------------


def shard_tree(tree, logical_tree, mesh, rules=None):
    """Place a pytree of host arrays onto ``mesh`` per logical axes.

    Works in multi-process SPMD worlds: every process passes the same
    full host values (the usual replicated-init pattern) and each device
    materializes only its shard via ``jax.make_array_from_callback``.
    Refreshes ``ray_tpu_train_param_shard_bytes`` with the process's
    resulting addressable bytes.
    """
    import jax
    import numpy as np

    from ...parallel.sharding import default_rules, named_sharding
    rules = rules or default_rules()

    def place(x, logical):
        if logical is None:
            sharding = named_sharding(mesh, (None,) * np.ndim(x), rules)
        else:
            sharding = named_sharding(mesh, logical, rules)
        host = np.asarray(x)
        # A REAL copy per shard, never a view: on the CPU substrate jax
        # may alias the callback's buffer zero-copy, and host[idx] of a
        # full-extent/replicated slice IS the caller's array — a later
        # in-place write to their host tree would silently corrupt the
        # placed device values (ascontiguousarray does not copy
        # already-contiguous views, so it is not a guard here).
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx].copy())

    out = jax.tree.map(place, tree, logical_tree,
                       is_leaf=lambda x: x is None)
    note_param_shard_bytes(out)
    return out


def shard_batch_tree(batch, mesh, rules=None):
    """Place per-process batch leaves onto the mesh's data axes: each
    process contributes its LOCAL rows of the global batch (leading dim
    over (dp, fsdp), seq over sp when sized)."""
    import jax

    from ...parallel.spmd import batch_pspec
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, batch_pspec(mesh, rules))
    return jax.tree.map(
        lambda v: jax.make_array_from_process_local_data(sharding, v),
        batch)
