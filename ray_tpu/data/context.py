"""Execution context for the Data library (reference:
python/ray/data/context.py DataContext — global execution knobs).

``op_memory_budget_bytes`` drives per-operator backpressure: each
streaming stage sizes its in-flight window from the budget divided by the
operator's OBSERVED average block size (EMA), clamped to
[min_in_flight, max_in_flight] — small blocks pipeline deep, huge blocks
throttle to a couple in flight (reference:
_internal/execution/backpressure_policy/ concurrency caps +
reservation-based memory scheduling).
"""

from __future__ import annotations

from typing import Optional


class DataContext:
    _instance: Optional["DataContext"] = None

    def __init__(self):
        self.op_memory_budget_bytes: int = 256 << 20
        self.min_in_flight: int = 2
        self.max_in_flight: int = 32
        # Window used before any block size has been observed.
        self.initial_in_flight: int = 8
        # Whether streaming iteration yields blocks in plan order.  False
        # (the reference's ExecutionOptions.preserve_order default) lets
        # iter_batches surface whichever block finishes first, so one slow
        # task never head-of-line-blocks the consumer.  take()/execute()
        # always preserve order regardless.
        self.preserve_order: bool = False
        # Physical block layout: "numpy" (dict of ndarrays — the
        # device-feed default) or "arrow" (pyarrow Tables: parquet scans
        # and slice/take/concat stay zero-copy; numpy materializes only
        # at the consumer boundary).  Reference:
        # _internal/arrow_block.py Arrow-native blocks.
        self.block_format: str = "numpy"

    @classmethod
    def get(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = DataContext()
        return cls._instance
