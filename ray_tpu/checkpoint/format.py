"""Checkpoint wire format v1: sharded per-rank layout with an atomic manifest.

Layout of one checkpoint directory (``<storage>/<experiment>/checkpoint_<step>``)::

    shard-00000-of-00002.bin      per-rank data: concatenated raw leaf chunks
    shard-00000-of-00002.index.json  per-rank chunk index (leaf -> offsets/slices)
    skeleton.pkl                  pytree structure with _LeafMarker leaves (rank 0)
    manifest.json                 global commit record (coordinator, atomic)

Commit protocol: every rank writes only its shard pair (each file lands via
tmp-file + ``os.replace``), then acks the coordinator; the coordinator writes
``manifest.json`` — also tmp + ``os.replace`` — only after ALL ranks acked.
A directory without a valid manifest is, by definition, not a checkpoint: a
crash at any point mid-save can never corrupt "latest".

The manifest carries a self-checksum (sha256 over its canonical JSON minus
the ``checksum`` field) plus per-shard byte sizes and crc32s, so torn or
bit-rotted checkpoints fail closed at restore/inspect time.

Resharding: each leaf chunk records the slice of the *global* array it holds
(``index`` = per-dim [start, stop]).  Restore assembles any target slicing
from any saved world size — exact-match chunks take a fast path (single
contiguous read), partial overlaps go through the generic gather in
``sharding.py``.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import tempfile
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import sharding

FORMAT_NAME = "ray_tpu_ckpt_v1"
MANIFEST = "manifest.json"
SKELETON = "skeleton.pkl"


class CheckpointError(Exception):
    """A checkpoint failed to serialize, commit, validate, or restore."""


class _LeafMarker:
    """Placeholder leaf in the pickled structure skeleton.

    ``jax.tree.map(lambda x: None, tree)`` would NOT work here: None is not
    a pytree leaf, so the skeleton would flatten to zero leaves.  A marker
    instance survives flattening and pickles from a stable module path.
    """

    def __repr__(self):
        return "<leaf>"


def _key_str(path) -> str:
    """Stable "a/b/0" string for a jax key path."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


@dataclass
class LeafChunk:
    """One rank-local piece of one leaf: ``array`` covers ``index`` of the
    leaf's global shape."""
    index: Tuple[Tuple[int, int], ...]
    array: Any  # np.ndarray (host)


@dataclass
class LeafSnapshot:
    dtype: str
    global_shape: Tuple[int, ...]
    chunks: List[LeafChunk] = field(default_factory=list)
    #: Non-array leaf (int/str/config object...): pickled payload instead
    #: of chunks.
    obj_payload: Optional[bytes] = None


@dataclass
class Snapshot:
    """Host-side copy of this rank's pytree shards — the only thing whose
    creation blocks the train step; everything downstream of it runs on
    the writer thread."""
    leaves: Dict[str, LeafSnapshot]
    skeleton_pkl: bytes
    nbytes: int


def _is_array(x: Any) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def snapshot_tree(tree: Any,
                  shard_spec: Optional[Callable] = None) -> Snapshot:
    """Device arrays -> host numpy chunks (the blocking part of a save).

    ``shard_spec(key, leaf)`` may return ``(global_shape, index)`` to declare
    that this rank holds only ``index`` of a larger global array (CPU/numpy
    leaves default to fully-owned).  jax Arrays with a non-trivial sharding
    are decomposed through ``addressable_shards`` automatically; replicas
    (replica_id != 0) are skipped so a replicated leaf is written once.

    Plain numpy / fully-replicated leaves WITHOUT a shard_spec are written
    in full by every rank (no cross-rank protocol exists at snapshot time
    to elect a writer): restore dedups identical regions preferring the
    lowest rank, so rank-divergent unsharded leaves (per-rank rng state)
    restore rank 0's values everywhere — declare a shard_spec for leaves
    where that matters, and to avoid world_size x write amplification on
    large replicated trees.
    """
    import jax
    import numpy as np

    flat, _treedef = jax.tree_util.tree_flatten_with_path(tree)
    skeleton = jax.tree.map(lambda x: _LeafMarker(), tree)
    leaves: Dict[str, LeafSnapshot] = {}
    nbytes = 0
    for path, leaf in flat:
        key = _key_str(path)
        if not _is_array(leaf):
            leaves[key] = LeafSnapshot(
                dtype="object", global_shape=(),
                obj_payload=pickle.dumps(leaf, protocol=5))
            nbytes += len(leaves[key].obj_payload)
            continue
        spec = shard_spec(key, leaf) if shard_spec is not None else None
        shards = getattr(leaf, "addressable_shards", None)
        if spec is not None:
            global_shape, index = spec
            arr = np.asarray(jax.device_get(leaf))
            snap = LeafSnapshot(str(arr.dtype), tuple(global_shape))
            snap.chunks.append(
                LeafChunk(sharding.normalize_index(index, global_shape),
                          np.ascontiguousarray(arr)))
        elif shards is not None and not getattr(
                leaf, "is_fully_replicated", True):
            snap = LeafSnapshot(str(np.dtype(leaf.dtype)), tuple(leaf.shape))
            for sh in shards:
                if getattr(sh, "replica_id", 0) != 0:
                    continue
                arr = np.ascontiguousarray(np.asarray(sh.data))
                snap.chunks.append(LeafChunk(
                    sharding.index_from_slices(sh.index, leaf.shape), arr))
        else:
            arr = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
            snap = LeafSnapshot(str(arr.dtype), tuple(arr.shape))
            snap.chunks.append(
                LeafChunk(sharding.full_index(arr.shape), arr))
        leaves[key] = snap
        nbytes += sum(c.array.nbytes for c in snap.chunks)
    return Snapshot(leaves=leaves, skeleton_pkl=pickle.dumps(
        skeleton, protocol=5), nbytes=nbytes)


# -- shard build/write ------------------------------------------------------


def shard_basename(rank: int, world: int) -> str:
    return f"shard-{rank:05d}-of-{world:05d}"


def build_shard(snapshot: Snapshot, rank: int, world: int,
                step: int) -> Tuple[Dict[str, Any], bytes]:
    """Serialize one rank's snapshot into (index dict, data blob)."""
    buf = io.BytesIO()
    index_leaves: Dict[str, Any] = {}
    for key, snap in snapshot.leaves.items():
        if snap.obj_payload is not None:
            off = buf.tell()
            buf.write(snap.obj_payload)
            index_leaves[key] = {
                "kind": "object", "offset": off,
                "nbytes": len(snap.obj_payload),
                "crc32": zlib.crc32(snap.obj_payload) & 0xFFFFFFFF}
            continue
        chunks = []
        for c in snap.chunks:
            off = buf.tell()
            raw = c.array.tobytes()  # C-order raw bytes
            buf.write(raw)
            # Per-chunk crc: restores verify every byte range they
            # actually read, so bit-rot fails closed even on partial
            # (resharded) reads that never touch the whole file.
            chunks.append({"offset": off, "nbytes": len(raw),
                           "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                           "index": [list(p) for p in c.index]})
        index_leaves[key] = {
            "kind": "array", "dtype": snap.dtype,
            "global_shape": list(snap.global_shape), "chunks": chunks}
    blob = buf.getvalue()
    index = {
        "format": FORMAT_NAME,
        "step": step,
        "rank": rank,
        "world_size": world,
        "data_file": shard_basename(rank, world) + ".bin",
        "nbytes": len(blob),
        "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
        "leaves": index_leaves,
    }
    return index, blob


def write_bytes_atomic(path: str, data: bytes) -> None:
    """tmp-file + fsync + ``os.replace``: the path either holds the
    complete bytes or does not exist — never a torn prefix, and (with
    the fsync) never a size-correct zero-filled file after power loss
    on delayed-allocation filesystems."""
    from .._private import sanitizer
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=os.path.basename(path) + ".tmp")
    os.close(fd)
    try:
        # tracked_open: checkpoint write handles register with the leak
        # sanitizer while open (RAY_TPU_SANITIZE=1), so a writer that
        # wedges mid-publish is attributable in the shutdown diff.
        with sanitizer.tracked_open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_shard(dirpath: str, index: Dict[str, Any], blob: bytes,
                skeleton_pkl: Optional[bytes] = None) -> None:
    """Publish one rank's shard pair (and, on rank 0, the skeleton)."""
    os.makedirs(dirpath, exist_ok=True)
    write_bytes_atomic(os.path.join(dirpath, index["data_file"]), blob)
    if skeleton_pkl is not None:
        write_bytes_atomic(os.path.join(dirpath, SKELETON), skeleton_pkl)
    base = shard_basename(index["rank"], index["world_size"])
    write_bytes_atomic(os.path.join(dirpath, base + ".index.json"),
                       json.dumps(index).encode())


# -- manifest ----------------------------------------------------------------


def manifest_checksum(manifest: Dict[str, Any]) -> str:
    body = {k: v for k, v in manifest.items() if k != "checksum"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def build_manifest(dirpath: str, step: int, world: int,
                   metrics: Optional[Dict[str, Any]] = None,
                   replica: bool = False) -> Dict[str, Any]:
    """Assemble the global manifest from the per-rank shard indexes.

    Raises CheckpointError when any rank's shard pair is missing or its
    data file does not match the index — the coordinator must never
    commit a checkpoint it cannot prove complete.
    """
    shards = []
    leaves: Dict[str, Any] = {}
    for rank in range(world):
        base = shard_basename(rank, world)
        ipath = os.path.join(dirpath, base + ".index.json")
        try:
            with open(ipath, "rb") as f:
                index = json.loads(f.read())
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"rank {rank} shard index missing/unreadable: {e}")
        dpath = os.path.join(dirpath, index["data_file"])
        try:
            size = os.path.getsize(dpath)
        except OSError:
            raise CheckpointError(f"rank {rank} data file missing: {dpath}")
        if size != index["nbytes"]:
            raise CheckpointError(
                f"rank {rank} data file is {size}B, index says "
                f"{index['nbytes']}B")
        shards.append({"rank": rank, "data_file": index["data_file"],
                       "index_file": base + ".index.json",
                       "nbytes": index["nbytes"], "crc32": index["crc32"]})
        for key, spec in index["leaves"].items():
            if spec["kind"] == "array" and key not in leaves:
                leaves[key] = {"dtype": spec["dtype"],
                               "global_shape": spec["global_shape"]}
    manifest = {
        "format": FORMAT_NAME,
        "step": step,
        "world_size": world,
        "time": time.time(),
        "replica": bool(replica),
        "metrics": dict(metrics or {}),
        "shards": shards,
        "leaves": leaves,
        "total_bytes": sum(s["nbytes"] for s in shards),
    }
    manifest["checksum"] = manifest_checksum(manifest)
    return manifest


def commit_manifest(dirpath: str, manifest: Dict[str, Any]) -> None:
    """The commit point: after this replace, the checkpoint exists."""
    write_bytes_atomic(os.path.join(dirpath, MANIFEST),
                       json.dumps(manifest, indent=1).encode())


def read_manifest(dirpath: str) -> Dict[str, Any]:
    with open(os.path.join(dirpath, MANIFEST), "rb") as f:
        manifest = json.loads(f.read())
    if manifest.get("checksum") != manifest_checksum(manifest):
        raise CheckpointError(f"manifest checksum mismatch in {dirpath}")
    return manifest


def is_committed(dirpath: str) -> bool:
    return os.path.exists(os.path.join(dirpath, MANIFEST))


def verify_checkpoint(dirpath: str, deep: bool = False) -> List[str]:
    """Validity problems for a checkpoint dir ([] = valid).

    Shallow: manifest parses, self-checksum matches, every shard file
    exists with the manifest's byte size.  ``deep`` additionally re-reads
    every data file and checks its crc32.
    """
    problems: List[str] = []
    try:
        manifest = read_manifest(dirpath)
    except FileNotFoundError:
        return ["no manifest (uncommitted or not a checkpoint)"]
    except (CheckpointError, ValueError, OSError) as e:
        return [f"manifest invalid: {e}"]
    for sh in manifest["shards"]:
        dpath = os.path.join(dirpath, sh["data_file"])
        if not os.path.exists(dpath):
            problems.append(f"missing {sh['data_file']}")
            continue
        size = os.path.getsize(dpath)
        if size != sh["nbytes"]:
            problems.append(
                f"{sh['data_file']}: {size}B on disk, manifest says "
                f"{sh['nbytes']}B")
            continue
        if deep:
            with open(dpath, "rb") as f:
                crc = zlib.crc32(f.read()) & 0xFFFFFFFF
            if crc != sh["crc32"]:
                problems.append(f"{sh['data_file']}: crc32 mismatch")
    return problems


# -- restore -----------------------------------------------------------------


class _FileShardSource:
    """Reads leaf chunks of one rank's shard straight off its data file —
    only the byte ranges a restore actually needs are read."""

    def __init__(self, dirpath: str, index: Dict[str, Any]):
        self.index = index
        self._path = os.path.join(dirpath, index["data_file"])

    def read(self, offset: int, nbytes: int) -> bytes:
        with open(self._path, "rb") as f:
            f.seek(offset)
            return f.read(nbytes)


class _BlobShardSource:
    """In-memory shard (emergency replica restore path)."""

    def __init__(self, index: Dict[str, Any], blob: bytes):
        self.index = index
        self._blob = blob

    def read(self, offset: int, nbytes: int) -> bytes:
        return self._blob[offset:offset + nbytes]


def _load_skeleton(dirpath: str):
    with open(os.path.join(dirpath, SKELETON), "rb") as f:
        return pickle.loads(f.read())


def _assemble(sources: List[Any], placement: Optional[Callable],
              skeleton: Any) -> Any:
    """Gather this rank's slices of every leaf from the shard sources.

    ``placement(key, global_shape) -> index`` names the slice the caller
    wants (None = the full global array).  The single-host overlap fast
    path — a stored chunk exactly matching the requested index — is one
    contiguous read with no copy-assembly; anything else goes through the
    generic region gather.
    """
    import jax
    import numpy as np

    # leaf key -> (spec, [(source, chunk_meta)])
    by_key: Dict[str, Tuple[Dict[str, Any], List[Tuple[Any, Dict]]]] = {}
    for src in sources:
        for key, spec in src.index["leaves"].items():
            entry = by_key.setdefault(key, (spec, []))
            if spec["kind"] == "array":
                for c in src.index["leaves"][key]["chunks"]:
                    entry[1].append((src, c))
            else:
                entry[1].append((src, spec))

    def _checked_read(src, meta) -> bytes:
        raw = src.read(meta["offset"], meta["nbytes"])
        crc = meta.get("crc32")
        if len(raw) != meta["nbytes"] or (
                crc is not None and
                (zlib.crc32(raw) & 0xFFFFFFFF) != crc):
            raise CheckpointError(
                f"shard chunk at offset {meta['offset']} failed crc/size "
                f"verification (bit rot or torn write)")
        return raw

    def _restore_leaf(key: str):
        if key not in by_key:
            raise CheckpointError(f"leaf {key!r} absent from all shards")
        spec, stored = by_key[key]
        if spec["kind"] == "object":
            src, meta = stored[0]
            return pickle.loads(_checked_read(src, meta))
        global_shape = tuple(spec["global_shape"])
        dtype = np.dtype(spec["dtype"])
        target = sharding.normalize_index(
            placement(key, global_shape) if placement is not None else None,
            global_shape)
        # Dedup identical stored regions (replicated leaves written by
        # several ranks): keep the first occurrence of each index.
        seen = set()
        chunks = []
        for src, c in stored:
            cidx = tuple(tuple(p) for p in c["index"])
            if cidx in seen:
                continue
            seen.add(cidx)
            chunks.append((src, c, cidx))
        # Fast path: a stored chunk IS the requested slice.
        for src, c, cidx in chunks:
            if cidx == target:
                raw = _checked_read(src, c)
                return np.frombuffer(raw, dtype=dtype).reshape(
                    sharding.index_shape(target)).copy()
        # Generic gather: copy every overlapping region.  Coverage is
        # tracked as a mask UNION — overlapping chunks must not be able
        # to sum past a hole and hand back uninitialized memory.
        out = np.empty(sharding.index_shape(target), dtype=dtype)
        covered = np.zeros(sharding.index_shape(target), dtype=bool)
        for src, c, cidx in chunks:
            inter = sharding.intersect(cidx, target)
            if inter is None:
                continue
            raw = _checked_read(src, c)
            arr = np.frombuffer(raw, dtype=dtype).reshape(
                sharding.index_shape(cidx))
            sharding.copy_region(out, target, arr, cidx, inter)
            sharding.copy_region(covered, target, None, None, inter,
                                 fill=True)
        missing = covered.size - int(np.count_nonzero(covered))
        if missing:
            raise CheckpointError(
                f"leaf {key!r}: stored shards leave {missing} of "
                f"{covered.size} requested elements uncovered "
                f"(target {target})")
        return out

    flat, treedef = jax.tree_util.tree_flatten_with_path(
        skeleton, is_leaf=lambda x: isinstance(x, _LeafMarker))
    restored = [_restore_leaf(_key_str(path)) for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, restored)


def restore_tree(dirpath: str, placement: Optional[Callable] = None,
                 blobs: Optional[Dict[int, Tuple[Dict, bytes]]] = None) -> Any:
    """Restore a pytree from a committed checkpoint directory.

    ``placement(key, global_shape) -> index`` reshards on the fly (None =
    assemble full global arrays).  ``blobs`` maps rank -> (index, data
    bytes) for shards already resident in memory (emergency replicas);
    ranks absent from ``blobs`` fall back to their on-disk files.
    """
    manifest = read_manifest(dirpath)
    skeleton = _load_skeleton(dirpath)
    sources: List[Any] = []
    for sh in manifest["shards"]:
        if blobs is not None and sh["rank"] in blobs:
            index, blob = blobs[sh["rank"]]
            sources.append(_BlobShardSource(index, blob))
            continue
        ipath = os.path.join(dirpath, sh["index_file"])
        with open(ipath, "rb") as f:
            index = json.loads(f.read())
        sources.append(_FileShardSource(dirpath, index))
    return _assemble(sources, placement, skeleton)


# -- legacy single-file pickle format (pre-subsystem compat) ----------------


def save_pytree(tree: Any, path: str, use_orbax: bool = False) -> None:
    """Legacy synchronous save: device arrays -> host numpy -> one pickle.

    Kept as the compat path behind ``train._checkpoint.save_pytree`` and
    as the sync baseline in ``bench.py --spec checkpoint``.
    """
    import time as _time

    import jax
    import numpy as np
    t0 = _time.perf_counter()
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
    if use_orbax:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "orbax"), host)
    else:
        buf = pickle.dumps(host, protocol=5)
        write_bytes_atomic(os.path.join(path, "pytree.pkl"), buf)
    _note_legacy("save", _time.perf_counter() - t0)


def load_pytree(path: str, use_orbax: bool = False) -> Any:
    import time as _time
    t0 = _time.perf_counter()
    if use_orbax:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        out = ckptr.restore(os.path.join(path, "orbax"))
    elif is_committed(path):
        out = restore_tree(path)
    else:
        with open(os.path.join(path, "pytree.pkl"), "rb") as f:
            out = pickle.load(f)
    _note_legacy("restore", _time.perf_counter() - t0)
    return out


def _note_legacy(op: str, seconds: float) -> None:
    try:
        from ..util import telemetry
    except Exception:
        return
    telemetry.observe("ray_tpu_train_checkpoint_seconds", seconds,
                      tags={"op": op})
    telemetry.note_checkpoint_seconds(seconds)
    if op == "restore":
        telemetry.observe("ray_tpu_ckpt_restore_seconds", seconds,
                          tags={"source": "disk"})
