"""TPU accelerator plugin: detection, topology, chip visibility.

Models the reference's accelerator plugin system (reference:
python/ray/_private/accelerators/accelerator.py:16 AcceleratorManager ABC;
TPU implementation python/ray/_private/accelerators/tpu.py:345 — resource
name "TPU", TPU_VISIBLE_CHIPS isolation, per-generation chips/host logic
:237, slice-head marker resource :670, topology validation :426).

Detection deliberately avoids importing jax in the driver: initializing the
TPU runtime takes exclusive hold of the chips, which must stay free for
worker processes.  Chips are discovered from the device tree / environment
instead, the same way the reference reads GCE metadata and env vars.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional

from .._private.config import Config
from .accelerator import AcceleratorManager, register_accelerator

# Generation -> default chips per host for common slices (reference:
# tpu.py:237 per-generation logic).
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5e": 8, "v5p": 4, "v6e": 8}

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5litepod-256"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_NAME_ENV = "TPU_NAME"
MEGASCALE_COORDINATOR_ENV = "MEGASCALE_COORDINATOR_ADDRESS"
MEGASCALE_NUM_SLICES_ENV = "MEGASCALE_NUM_SLICES"
MEGASCALE_SLICE_ID_ENV = "MEGASCALE_SLICE_ID"


class TPUAcceleratorManager(AcceleratorManager):
    resource_name = "TPU"

    @staticmethod
    def visibility_env(chip_ids: List[int]) -> Dict[str, str]:
        return {TPU_VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chip_ids)}

    @staticmethod
    def detect_num_chips() -> int:
        """Chips on this host, without initializing a TPU runtime."""
        override = Config.get("tpu_chips_per_host_override")
        if override:
            return override
        visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if visible:
            return len([c for c in visible.split(",") if c.strip() != ""])
        # Device nodes: /dev/accel* (TPU VM) or vfio for newer stacks.
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        vfio = glob.glob("/dev/vfio/[0-9]*")
        if vfio:
            return len(vfio)
        acc_type = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if acc_type:
            gen = TPUAcceleratorManager.generation_from_type(acc_type)
            return _CHIPS_PER_HOST.get(gen, 4)
        return 0

    @staticmethod
    def generation_from_type(accelerator_type: str) -> str:
        """'v5litepod-256' -> 'v5e', 'v4-8' -> 'v4'."""
        m = re.match(r"v(\d+)(lite)?(pod|p|e)?", accelerator_type or "")
        if not m:
            return "unknown"
        ver = m.group(1)
        if m.group(2) == "lite" or m.group(3) == "e":
            return f"v{ver}e"
        if m.group(3) == "p" and ver == "5":
            return "v5p"
        return f"v{ver}"

    @staticmethod
    def accelerator_type() -> Optional[str]:
        return os.environ.get(TPU_ACCELERATOR_TYPE_ENV)

    @staticmethod
    def slice_head_resource_name() -> Optional[str]:
        """Marker resource advertised only by a slice's worker 0, used for
        gang-scheduling one coordinator per slice (reference: tpu.py:670
        TPU-{version}-head)."""
        acc_type = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if not acc_type:
            return None
        worker_id = os.environ.get(TPU_WORKER_ID_ENV, "0")
        if worker_id != "0":
            return None
        gen = TPUAcceleratorManager.generation_from_type(acc_type)
        return f"TPU-{gen}-head"

    @staticmethod
    def num_hosts_for_type(accelerator_type: str) -> int:
        """'v5litepod-256' -> 32 hosts (256 chips / 8 per host)."""
        m = re.search(r"-(\d+)$", accelerator_type or "")
        if not m:
            return 1
        chips = int(m.group(1))
        gen = TPUAcceleratorManager.generation_from_type(accelerator_type)
        per_host = _CHIPS_PER_HOST.get(gen, 4)
        return max(1, chips // per_host)

    @staticmethod
    def set_visible_chips(chip_ids: List[int]) -> None:
        os.environ.update(TPUAcceleratorManager.visibility_env(chip_ids))

    @staticmethod
    def get_current_process_visible_chips() -> Optional[List[int]]:
        v = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if v is None:
            return None
        return [int(c) for c in v.split(",") if c.strip() != ""]


def get_tpu_coordinator_env_vars(slice_id: int, num_slices: int,
                                 coordinator_address: str) -> Dict[str, str]:
    """MEGASCALE env plumbing for multi-slice (DCN) jobs (reference:
    python/ray/util/tpu.py:206 get_tpu_coordinator_env_vars and
    python/ray/train/v2/jax/config.py:95-103)."""
    if num_slices <= 1:
        return {}
    return {
        MEGASCALE_COORDINATOR_ENV: coordinator_address,
        MEGASCALE_NUM_SLICES_ENV: str(num_slices),
        MEGASCALE_SLICE_ID_ENV: str(slice_id),
    }


register_accelerator(TPUAcceleratorManager)
