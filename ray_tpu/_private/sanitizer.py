"""Runtime resource-leak sanitizer (``RAY_TPU_SANITIZE=1``).

The static RT3xx rules prove per-function release discipline; this is
the runtime twin — the ASan/LSan of the control plane.  When enabled
(env var at ``import ray_tpu`` time, or :func:`install` directly) it
keeps lightweight registries of the resources whose leaks erode
long-run goodput:

* **framework threads** — ``threading.Thread.start`` is patched to
  record a creation-site stack for every thread started *from* the
  ``ray_tpu`` tree (test/user threads are ignored); the
  :func:`spawn` helper is the sanctioned fire-and-forget spawn path
  (RT301 recognizes it as tracked registration),
* **pinned objects** — ``ctl_pin_object`` / ``ctl_unpin_object`` report
  here, so an unpaired emergency-replica pin is visible,
* **tracked file handles** — debug-bundle / checkpoint writers open
  through :func:`tracked_open`,
* **named actors** — registration reports name + creation site;
  session-lifetime-by-design names (serve controller, checkpoint
  replica holders) are declared with :func:`session_scoped`.

:func:`snapshot` (called by ``init_runtime``) records the baseline;
``ray_tpu.shutdown()`` calls :func:`pre_shutdown` (named actors must be
inspected before teardown marks everything DEAD) and, after the runtime
is down, :func:`check_after_shutdown` — a nonzero diff raises
:class:`LeakError` listing every leaked resource with its creation-site
summary.  ``tests/conftest.py`` turns the sanitizer on for the whole
tier-1 suite, so every existing test doubles as a leak test.  Reports
also land in flight-recorder debug bundles as ``leak_findings.json``.

Scope: the check runs in the *driver* process (worker-process threads
die with their process).  Overhead when disabled is zero — nothing is
patched; when enabled it is one dict write per tracked event
(``bench.py --spec sanitize`` keeps it under the 2% budget).
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
import traceback
import weakref
from typing import Any, Dict, List, Optional

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Frames kept per creation-site summary.
_STACK_DEPTH = 5

#: Post-shutdown grace for framework threads to wind down before a
#: still-alive one counts as leaked.
DEFAULT_GRACE_S = 4.0


class LeakError(RuntimeError):
    """Raised at shutdown when the sanitizer's diff is nonzero."""


class _State:
    def __init__(self) -> None:
        self.mu = threading.Lock()
        self.installed = False
        # thread -> {"name", "site", "stack", "tracked"} (weak keys: a
        # dead, collected thread can never be reported).
        self.threads: "weakref.WeakKeyDictionary[threading.Thread, dict]" \
            = weakref.WeakKeyDictionary()
        self.pins: Dict[str, dict] = {}          # oid hex -> info
        self.files: Dict[int, dict] = {}         # id(wrapper) -> info
        self.named_actors: Dict[str, dict] = {}  # "ns/name" -> info
        self.session_patterns: List[str] = []
        self.thread_allow: List[str] = []
        self.baseline_threads: set = set()       # Thread idents
        self.baseline_pins: set = set()
        self.baseline_files: set = set()
        self.baseline_named: set = set()


_state = _State()
_real_thread_start = threading.Thread.start


_SELF_FILE = os.path.abspath(__file__)

#: Frames walked looking for the creation site.  A bounded
#: ``sys._getframe`` walk, NOT ``traceback.extract_stack()`` — the full
#: extract (deep pytest stacks + linecache source reads) costs ~100µs
#: per call, which multiplied by every framework thread start blew the
#: sanitizer's 2% budget on the core task/actor loop.
_WALK_DEPTH = 14


def _site_and_stack(skip_self: bool = True):
    """(innermost ray_tpu frame "file:line", short outer->inner stack)
    — or ``(None, stack)`` when no frame is inside the package (not
    framework-created)."""
    import sys
    frames: List[str] = []
    site = None
    try:
        f = sys._getframe(2 if skip_self else 1)
    except ValueError:
        f = None
    depth = 0
    while f is not None and depth < _WALK_DEPTH:
        fn = f.f_code.co_filename
        frames.append(f"{os.path.basename(fn)}:{f.f_lineno} "
                      f"in {f.f_code.co_name}")
        if site is None and fn.startswith(_PKG_DIR) and fn != _SELF_FILE:
            site = f"{os.path.relpath(fn, os.path.dirname(_PKG_DIR))}" \
                   f":{f.f_lineno}"
        f = f.f_back
        depth += 1
    frames.reverse()
    return site, frames[-_STACK_DEPTH:]


# -- install ---------------------------------------------------------------


def _recording_start(self: threading.Thread) -> None:
    if _state.installed and self not in _state.threads:
        # Threads registered by spawn() keep their entry (and its
        # tracked=True flag) — this path only records direct
        # Thread.start() calls made from framework code.
        site, stack = _site_and_stack()
        if site is not None:
            with _state.mu:
                _state.threads[self] = {
                    "name": self.name, "site": site, "stack": stack,
                    "tracked": False, "time": time.time()}
    _real_thread_start(self)


def install() -> None:
    """Patch ``threading.Thread.start`` to record framework creation
    sites.  Idempotent; :func:`uninstall` restores the original."""
    with _state.mu:
        if _state.installed:
            return
        _state.installed = True
    threading.Thread.start = _recording_start  # type: ignore[assignment]


def uninstall() -> None:
    with _state.mu:
        if not _state.installed:
            return
        _state.installed = False
    threading.Thread.start = _real_thread_start  # type: ignore[assignment]


def is_enabled() -> bool:
    return _state.installed


# -- spawn helper ----------------------------------------------------------


def spawn(target, *, name: Optional[str] = None, args: tuple = (),
          kwargs: Optional[dict] = None,
          daemon: bool = True) -> threading.Thread:
    """Create, register and start a framework background thread — THE
    sanctioned fire-and-forget spawn (RT301 counts it as registration
    in a tracked set; a bare ``threading.Thread(...).start()`` with no
    reachable join is a lint finding)."""
    t = threading.Thread(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=daemon)
    if _state.installed:
        site, stack = _site_and_stack()
        with _state.mu:
            _state.threads[t] = {"name": t.name, "site": site or "<app>",
                                 "stack": stack, "tracked": True,
                                 "time": time.time()}
    t.start()
    return t


def allow_thread(name_prefix: str) -> None:
    """Declare a thread-name prefix that may legitimately outlive
    ``shutdown()`` (use sparingly; prefer joining at teardown)."""
    with _state.mu:
        if name_prefix not in _state.thread_allow:
            _state.thread_allow.append(name_prefix)


# -- pins ------------------------------------------------------------------


def note_pin(oid_hex: str) -> None:
    if not _state.installed:
        return
    site, stack = _site_and_stack()
    with _state.mu:
        info = _state.pins.setdefault(
            oid_hex, {"count": 0, "site": site or "<rpc>",
                      "stack": stack, "time": time.time()})
        info["count"] += 1


def note_unpin(oid_hex: str) -> None:
    if not _state.installed:
        return
    with _state.mu:
        info = _state.pins.get(oid_hex)
        if info is None:
            return
        info["count"] -= 1
        if info["count"] <= 0:
            del _state.pins[oid_hex]


# -- tracked files ---------------------------------------------------------


class TrackedFile:
    """Thin wrapper whose ``close`` unregisters; returned by
    :func:`tracked_open`."""

    def __init__(self, f, info: dict):
        self._f = f
        self._info = info

    def __getattr__(self, name: str) -> Any:
        return getattr(self._f, name)

    def __enter__(self) -> "TrackedFile":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def __iter__(self):
        return iter(self._f)

    def close(self) -> None:
        with _state.mu:
            _state.files.pop(id(self), None)
        self._f.close()


def tracked_open(path: str, mode: str = "r", **kw):
    """``open()`` that registers the handle while the sanitizer is on
    (debug-bundle/checkpoint writers use this, so a handle that never
    closes shows up in the shutdown diff with its opening site)."""
    f = open(path, mode, **kw)
    if not _state.installed:
        return f
    site, stack = _site_and_stack()
    tf = TrackedFile(f, {})
    with _state.mu:
        _state.files[id(tf)] = {"path": path, "mode": mode,
                                "site": site or "<app>", "stack": stack,
                                "time": time.time()}
    return tf


# -- named actors ----------------------------------------------------------


def _framework_created() -> Optional[str]:
    """Innermost *subsystem* frame (under ray_tpu/ but outside
    ``_private``/``scripts``) on the current stack, or None.  User code
    creating a named actor goes straight through the ``_private`` API
    machinery; framework subsystems (serve, checkpoint, ...) add their
    own frame."""
    for fr in reversed(traceback.extract_stack()[:-2]):
        fn = os.path.abspath(fr.filename)
        if not fn.startswith(_PKG_DIR):
            continue
        rel = os.path.relpath(fn, _PKG_DIR)
        top = rel.split(os.sep)[0]
        if top not in ("_private", "scripts", "__init__.py"):
            return f"ray_tpu/{rel}:{fr.lineno}"
    return None


def note_named_actor(name: str, namespace: str,
                     class_name: Optional[str] = None) -> None:
    """Record a *framework-created* named actor.  User-created named
    actors are their owner's business — cluster shutdown reaps them by
    design; only subsystem-owned ones must be cleaned up (or declared
    :func:`session_scoped`) and count as leaks."""
    if not _state.installed or not name:
        return
    fw_site = _framework_created()
    if fw_site is None:
        return
    _, stack = _site_and_stack()
    with _state.mu:
        _state.named_actors[f"{namespace}/{name}"] = {
            "name": name, "namespace": namespace,
            "class_name": class_name, "site": fw_site,
            "stack": stack, "time": time.time()}


def session_scoped(name: str) -> None:
    """Declare a named actor as session-lifetime by design (fnmatch
    pattern): it will not be reported at shutdown."""
    with _state.mu:
        if name not in _state.session_patterns:
            _state.session_patterns.append(name)


# -- snapshot / check ------------------------------------------------------


def snapshot(rt: Any = None) -> None:
    """Record the baseline at cluster start: resources alive NOW belong
    to the environment (or to a previous, already-reported cluster) and
    are never re-reported."""
    if not _state.installed:
        return
    with _state.mu:
        _state.baseline_threads = {
            t.ident for t in threading.enumerate() if t.ident is not None}
        _state.baseline_pins = set(_state.pins)
        _state.baseline_files = set(_state.files)
        _state.baseline_named = set(_state.named_actors)


def _live_named(rt: Any) -> List[dict]:
    """Framework-created named actors still alive in ``rt``, minus
    session-scoped and baseline names — must run BEFORE teardown marks
    actors DEAD."""
    out: List[dict] = []
    with _state.mu:
        recorded = {k: dict(v) for k, v in _state.named_actors.items()}
        baseline = set(_state.baseline_named)
        patterns = list(_state.session_patterns)
    for key, rec in recorded.items():
        if key in baseline:
            continue
        name, ns = rec["name"], rec["namespace"]
        if any(fnmatch.fnmatch(name, pat) for pat in patterns):
            continue
        try:
            info = rt.controller.get_named_actor(name, ns)
        except Exception:
            continue
        if info is None or getattr(info, "state", "DEAD") == "DEAD":
            continue
        rec["kind"] = "named_actor"
        out.append(rec)
    return out


def pre_shutdown(rt: Any, grace_s: float = 2.0) -> List[dict]:
    """First half of the shutdown gate (returns pending named-actor
    leaks; pass to :func:`check_after_shutdown`).  ``kill()`` is
    asynchronous — an actor its subsystem reaped moments ago may not
    have landed DEAD yet, so leaks get a short settle window."""
    if not _state.installed:
        return []
    leaks = _live_named(rt)
    deadline = time.monotonic() + grace_s
    while leaks and time.monotonic() < deadline:
        time.sleep(0.05)
        leaks = _live_named(rt)
    return leaks


def _leaked_now() -> List[dict]:
    out: List[dict] = []
    with _state.mu:
        for t, info in list(_state.threads.items()):
            if not t.is_alive() or t.ident in _state.baseline_threads:
                continue
            if any(t.name.startswith(p) for p in _state.thread_allow):
                continue
            rec = dict(info)
            rec["kind"] = "thread"
            rec["alive_thread"] = t
            out.append(rec)
        for oid, info in _state.pins.items():
            if oid in _state.baseline_pins:
                continue
            rec = dict(info)
            rec["kind"] = "pin"
            rec["object_id"] = oid
            out.append(rec)
        for fid, info in _state.files.items():
            if fid in _state.baseline_files:
                continue
            rec = dict(info)
            rec["kind"] = "file"
            out.append(rec)
    return out


def check_after_shutdown(pre: Optional[List[dict]] = None,
                         grace_s: Optional[float] = None) -> None:
    """Second half of the shutdown gate: wait up to ``grace_s`` (module
    default: :data:`DEFAULT_GRACE_S`) for framework threads to wind
    down, then raise :class:`LeakError` on any nonzero diff."""
    if not _state.installed:
        return
    if grace_s is None:
        grace_s = DEFAULT_GRACE_S
    pre = pre or []
    deadline = time.monotonic() + grace_s
    leaks = _leaked_now()
    # Only threads can resolve themselves (by exiting); wait the grace
    # out for them, not for pins/files that cannot un-leak.
    while any(rec["kind"] == "thread" for rec in leaks) and \
            time.monotonic() < deadline:
        time.sleep(0.05)
        leaks = _leaked_now()
    leaks = pre + leaks
    for rec in leaks:
        rec.pop("alive_thread", None)
    if leaks:
        raise LeakError(format_report(leaks))


def format_report(leaks: List[dict]) -> str:
    lines = [f"resource leak sanitizer: {len(leaks)} leaked resource(s) "
             f"at shutdown (RAY_TPU_SANITIZE=1)"]
    for rec in leaks:
        kind = rec.get("kind")
        if kind == "thread":
            head = f"[thread] {rec.get('name')} created at " \
                   f"{rec.get('site')}"
        elif kind == "pin":
            head = f"[pin] object {rec.get('object_id', '')[:16]} pinned " \
                   f"at {rec.get('site')}"
        elif kind == "file":
            head = f"[file] {rec.get('path')} ({rec.get('mode')}) opened " \
                   f"at {rec.get('site')}"
        else:
            head = f"[named_actor] {rec.get('namespace')}/" \
                   f"{rec.get('name')} ({rec.get('class_name')}) " \
                   f"created at {rec.get('site')}"
        lines.append("  " + head)
        for fr in rec.get("stack", [])[-_STACK_DEPTH:]:
            lines.append("      " + fr)
    lines.append("  (declare intentional session-lifetime resources via "
                 "_private.sanitizer.session_scoped/allow_thread, or fix "
                 "the missing release)")
    return "\n".join(lines)


def report() -> Dict[str, Any]:
    """Snapshot for the flight recorder's ``leak_findings.json``: every
    currently-tracked live resource with its creation site."""
    with _state.mu:
        threads = [
            {"name": t.name, "site": info.get("site"),
             "tracked": info.get("tracked"), "stack": info.get("stack")}
            for t, info in list(_state.threads.items()) if t.is_alive()]
        return {
            "enabled": _state.installed,
            "pid": os.getpid(),
            "threads": threads,
            "pins": [{"object_id": oid, "count": i.get("count"),
                      "site": i.get("site")}
                     for oid, i in _state.pins.items()],
            "files": [{"path": i.get("path"), "site": i.get("site")}
                      for i in _state.files.values()],
            "named_actors": [
                {"name": i.get("name"), "namespace": i.get("namespace"),
                 "class_name": i.get("class_name"), "site": i.get("site")}
                for i in _state.named_actors.values()],
            "session_scoped": list(_state.session_patterns),
        }


def _reset_for_tests() -> None:
    """Drop registries and baseline (test isolation; does not change
    installed state)."""
    with _state.mu:
        _state.threads = weakref.WeakKeyDictionary()
        _state.pins.clear()
        _state.files.clear()
        _state.named_actors.clear()
        _state.baseline_threads = set()
        _state.baseline_pins = set()
        _state.baseline_files = set()
        _state.baseline_named = set()
