"""Mixture-of-experts: top-k routing + expert-parallel dispatch.

Absent from the reference (SURVEY §2.4 EP row: delegated to vLLM) — built
natively.  The expert dimension carries the ``expert`` logical axis, so
under the ``ep`` mesh axis GSPMD partitions the expert einsums and inserts
the token all-to-all implied by the dispatch.  Round-1 implementation uses
dense dispatch (every expert sees every token, masked by routing weights):
exactly correct, MXU-friendly, and the partitioning already exercises EP;
a capacity-based sparse dispatch kernel is the planned optimization.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class RoutingInfo(NamedTuple):
    combine_weights: jax.Array  # [B, S, X] softmax weights, zero off top-k
    router_probs: jax.Array     # [B, S, X] full softmax (for aux loss)
    expert_index: jax.Array     # [B, S, k]


def top_k_routing(x, router_w, k: int = 2,
                  router_noise: float = 0.0,
                  rng: Optional[jax.Array] = None) -> RoutingInfo:
    """x: [B, S, E]; router_w: [E, X] -> routing info."""
    logits = jnp.einsum("bse,ex->bsx", x.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    if router_noise > 0.0 and rng is not None:
        logits = logits + router_noise * jax.random.normal(
            rng, logits.shape, jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    # Renormalize the selected experts' weights to sum to one.
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    combine = jnp.zeros_like(probs)
    combine = jnp.put_along_axis(
        combine, topi, topv, axis=-1, inplace=False) \
        if hasattr(jnp, "put_along_axis") else _scatter(combine, topi, topv)
    return RoutingInfo(combine, probs, topi)


def _scatter(zeros, idx, vals):
    one_hot = jax.nn.one_hot(idx, zeros.shape[-1], dtype=vals.dtype)
    return jnp.einsum("bskx,bsk->bsx", one_hot, vals)


def load_balancing_loss(info: RoutingInfo, num_experts: int) -> jax.Array:
    """Switch-transformer style aux loss."""
    me = jnp.mean(info.router_probs, axis=(0, 1))            # [X]
    ce = jnp.mean((info.combine_weights > 0).astype(jnp.float32), axis=(0, 1))
    return num_experts * jnp.sum(me * ce)


def capacity_dispatch(info: RoutingInfo, num_experts: int,
                      capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Build GShard-style dispatch/combine tensors with capacity dropping.

    Tokens are assigned slots within each expert in token order via a
    cumulative count; assignments beyond ``capacity`` are dropped (their
    contribution to the output is zero — the residual stream carries them).

    Returns (dispatch [T, X, C] one-hot float, combine [T, X, C]) over
    flattened tokens T = B*S.
    """
    B, S, X = info.combine_weights.shape
    k = info.expert_index.shape[-1]
    idx = info.expert_index.reshape(B * S, k)
    weights = info.combine_weights.reshape(B * S, X)

    counts = jnp.zeros((X,), jnp.int32)
    dispatch = jnp.zeros((B * S, X, capacity), jnp.float32)
    combine = jnp.zeros((B * S, X, capacity), jnp.float32)
    for j in range(k):
        oh = jax.nn.one_hot(idx[:, j], X, dtype=jnp.int32)     # [T, X]
        pos = jnp.cumsum(oh, axis=0) - 1 + counts[None, :]     # [T, X]
        keep = (pos < capacity) & (oh > 0)
        counts = counts + jnp.sum(oh * keep, axis=0)
        slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                              dtype=jnp.float32)               # [T, X, C]
        d_j = slot * keep[..., None].astype(jnp.float32)
        dispatch = dispatch + d_j
        w_j = jnp.take_along_axis(weights, idx[:, j:j + 1], axis=-1)
        combine = combine + d_j * w_j[..., None]
    return dispatch, combine


def moe_layer(x, router_w, w_gate, w_up, w_down, k: int = 2,
              rng: Optional[jax.Array] = None,
              router_noise: float = 0.0,
              capacity_factor: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """SwiGLU expert MLPs with top-k routing.

    x: [B, S, E]; router_w: [E, X]; w_gate/w_up: [X, E, M]; w_down: [X, M, E].
    Returns (output [B, S, E], aux_loss scalar).

    ``capacity_factor`` == 0 keeps the dense dispatch (every expert sees
    every token, masked — exact, but O(num_experts) FLOPs); > 0 switches to
    capacity-based sparse dispatch where each expert processes at most
    ``ceil(k * T * capacity_factor / X)`` token slots, so expert FLOPs
    scale as top_k * capacity_factor / num_experts of dense.  Under the
    ``ep`` mesh axis the dispatch/combine einsums lower to the token
    all-to-all (GShard recipe).
    """
    import math

    X = router_w.shape[-1]
    info = top_k_routing(x, router_w, k=k, rng=rng,
                         router_noise=router_noise)
    if capacity_factor and capacity_factor > 0.0:
        B, S, E = x.shape
        T = B * S
        capacity = max(int(math.ceil(k * T * capacity_factor / X)), 1)
        dispatch, combine = capacity_dispatch(info, X, capacity)
        xt = x.reshape(T, E)
        # Token all-to-all: [T, E] x [T, X, C] -> per-expert slot inputs.
        expert_in = jnp.einsum("te,txc->xce", xt,
                               dispatch.astype(x.dtype))
        gate = jnp.einsum("xce,xem->xcm", expert_in, w_gate)
        up = jnp.einsum("xce,xem->xcm", expert_in, w_up)
        h = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("xcm,xme->xce", h, w_down)
        out = jnp.einsum("xce,txc->te", expert_out,
                         combine.astype(expert_out.dtype))
        out = out.reshape(B, S, E)
    else:
        # Dense dispatch: compute all experts, weight by combine matrix.
        # Under the ep axis, each device computes only its expert shard
        # ("x" dim) and GSPMD reduces the combine einsum across ep.
        gate = jnp.einsum("bse,xem->bsxm", x, w_gate)
        up = jnp.einsum("bse,xem->bsxm", x, w_up)
        h = jax.nn.silu(gate) * up
        expert_out = jnp.einsum("bsxm,xme->bsxe", h, w_down)
        out = jnp.einsum("bsxe,bsx->bse", expert_out,
                         info.combine_weights.astype(expert_out.dtype))
    return out.astype(x.dtype), load_balancing_loss(info, X)
