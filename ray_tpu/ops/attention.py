"""Causal (GQA) attention: pallas flash kernels + jnp reference.

FlashAttention-2 on TPU, forward *and* backward as pallas kernels:

- Forward blocks over BOTH sequence axes — grid (B*H, Sq/bq, Sk/bk) with the
  K/V axis innermost ("arbitrary" semantics) so pallas double-buffers K/V
  block DMAs while the MXU works.  Online softmax state (running max m,
  denominator l, unnormalized accumulator) lives in VMEM scratch carried
  across K blocks; the [Sq, Sk] score matrix never exists in HBM.  The
  log-sum-exp is written out as a residual (broadcast over the 128-lane
  minor dim, the TPU-friendly layout the jax flash kernel also uses).
- Backward is two kernels: dq (grid over K blocks innermost, accumulating
  dq for a resident Q block) and dk/dv (grid over Q blocks innermost,
  accumulating dk/dv for a resident K/V block).  Both recompute probabilities
  from the saved LSE — one exp, no second softmax pass — with fp32
  accumulation and bf16 MXU inputs.
- Causal block-skipping: blocks strictly above the diagonal are predicated
  out with pl.when and their K/V DMAs are redirected to block 0 (the next
  useful block), so the skipped half of the grid costs neither FLOPs nor
  bandwidth.
- GQA is native: the K/V index maps collapse query heads onto their shared
  KV head; dk/dv are emitted per query head and group-summed outside only
  when kv_heads < heads.

``q_offset`` shifts query positions for causal masking so sequence-sharded
callers (ring attention) can flash-attend a mid-sequence Q shard.

Design provenance (patterns, not code): the reference delegates attention to
engines (SURVEY §2.4 SP/CP row — no in-repo kernel); the block/layout recipe
follows jax.experimental.pallas.ops.tpu.flash_attention (LSE lane broadcast,
dual-axis grid, prefetch-redirect on skipped causal blocks).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
LANES = 128


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        q_offset: int = 0):
    """Plain-jnp attention. q: [B, H, Sq, D]; k/v: [B, Hkv, Sk, D].

    ``q_offset`` shifts query positions for causal masking (used by
    sequence-sharded callers where the local Q block starts mid-sequence).
    """
    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if Hkv != H:
        group = H // Hkv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = jnp.arange(Sq) + q_offset
        kpos = jnp.arange(Sk)
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def _bcast_lanes(x128, n):
    """[rows, 128] lane-broadcast value -> [rows, n]."""
    if n == LANES:
        return x128
    if n % LANES == 0:
        return jnp.tile(x128, (1, n // LANES))
    if n < LANES:
        return x128[:, :n]
    raise NotImplementedError(f"n={n} not a multiple of {LANES}")


def _visible(qi, bq, ki, bk, q_offset):
    """Causal: does q block qi see any of k block ki?"""
    return (qi + 1) * bq - 1 + q_offset >= ki * bk


def _causal_mask_bias(s_shape, qi, bq, ki, bk, q_offset):
    row = jax.lax.broadcasted_iota(jnp.int32, s_shape, 0) + qi * bq + q_offset
    col = jax.lax.broadcasted_iota(jnp.int32, s_shape, 1) + ki * bk
    return jnp.where(col <= row, 0.0, MASK_VALUE)


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, nk, q_offset):
    # lse_ref is None when the caller doesn't need residuals (inference).
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    run = True if not causal else _visible(qi, block_q, ki, block_k, q_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]                                   # [bq, D]
        k = k_ref[0]                                   # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            s = s + _causal_mask_bias(s.shape, qi, block_q, ki, block_k,
                                      q_offset)
        m_prev = m_scr[...]                            # [bq, 128]
        l_prev = l_scr[...]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - _bcast_lanes(m_next, s.shape[1]))
        alpha = jnp.exp(m_prev - m_next)               # [bq, 128]
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1)[:, None]
        m_scr[...] = m_next
        v = v_ref[0]
        pv = jax.lax.dot(p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * _bcast_lanes(alpha, acc_scr.shape[1]) \
            + pv

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[...]
        l_inv = jnp.where(l == 0.0, 1.0, 1.0 / l)
        o_ref[0] = (acc_scr[...]
                    * _bcast_lanes(l_inv, acc_scr.shape[1])
                    ).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = m_scr[...] + jnp.log(jnp.where(l == 0.0, 1.0, l))


def _flash_forward(q, k, v, causal, scale, block_q, block_k, q_offset,
                   interpret, *, need_lse):
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:          # pragma: no cover
        pltpu = None

    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    if H % Hkv:
        raise ValueError(f"H={H} not divisible by Hkv={Hkv}")
    group = H // Hkv
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"seq ({Sq},{Sk}) not divisible by blocks ({block_q},{block_k})")
    nq, nk = Sq // block_q, Sk // block_k

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * Hkv, Sk, D)
    vr = v.reshape(B * Hkv, Sk, D)

    def q_index(bh, qi, ki):
        return (bh, qi, 0)

    def kv_index(bh, qi, ki):
        row = (bh // H) * Hkv + (bh % H) // group
        if causal:
            ki = jnp.where(
                _visible(qi, block_q, ki, block_k, q_offset), ki, 0)
        return (row, ki, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk, q_offset=q_offset)

    params = {}
    if pltpu is not None and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    out_specs = [pl.BlockSpec((1, block_q, D), q_index)]
    out_shape = [jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, block_q, LANES), q_index))
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, Sq, LANES), jnp.float32))
    else:
        # No LSE output at all: skip ~B*H*Sq*128 fp32 of dead HBM writes.
        kernel = functools.partial(
            lambda q, k, v, o, m, l, a, *, _k: _k(q, k, v, o, None, m, l, a),
            _k=kernel)

    res = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _vmem((block_q, LANES), jnp.float32),
            _vmem((block_q, LANES), jnp.float32),
            _vmem((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(qr, kr, vr)
    out = res[0].reshape(B, H, Sq, D)
    if not need_lse:
        return out, None
    return out, res[1][..., 0].reshape(B, H, Sq)


def _vmem(shape, dtype):
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        return pltpu.VMEM(shape, dtype)
    except ImportError:          # pragma: no cover
        return pl.MemoryRef(shape, dtype)


# ---------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref, dq_scr,
               *, scale, causal, block_q, block_k, nk, q_offset):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    run = True if not causal else _visible(qi, block_q, ki, block_k, q_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                               # [bq, 128]
        di = di_ref[0]                                 # [bq, 128]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = s + _causal_mask_bias(s.shape, qi, block_q, ki, block_k,
                                      q_offset)
        p = jnp.exp(s - _bcast_lanes(lse, s.shape[1]))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - _bcast_lanes(di, s.shape[1])) * scale
        dq_scr[...] += jax.lax.dot(ds.astype(k.dtype), k,
                                   preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, nq, q_offset):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    run = True if not causal else _visible(qi, block_q, ki, block_k, q_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        di = di_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            s = s + _causal_mask_bias(s.shape, qi, block_q, ki, block_k,
                                      q_offset)
        p = jnp.exp(s - _bcast_lanes(lse, s.shape[1]))
        dv_scr[...] += jax.lax.dot(
            p.T.astype(do.dtype), do, preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - _bcast_lanes(di, s.shape[1])) * scale
        dk_scr[...] += jax.lax.dot(
            ds.T.astype(q.dtype), q, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, dout, causal, scale, block_q, block_k,
                    q_offset, interpret):
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:          # pragma: no cover
        pltpu = None

    B, H, Sq, D = q.shape
    _, Hkv, Sk, _ = k.shape
    group = H // Hkv
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    nq, nk = Sq // block_q, Sk // block_k

    # delta_i = rowsum(dO * O): one fused elementwise+reduce pass in XLA.
    di = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * Hkv, Sk, D)
    vr = v.reshape(B * Hkv, Sk, D)
    dor = dout.reshape(B * H, Sq, D)
    # LSE/delta residuals broadcast over the lane dim (layout-friendly).
    lser = jnp.broadcast_to(lse.reshape(B * H, Sq)[..., None],
                            (B * H, Sq, LANES))
    dir_ = jnp.broadcast_to(di.reshape(B * H, Sq)[..., None],
                            (B * H, Sq, LANES))

    params = {}
    if pltpu is not None and not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    def kv_row(bh):
        return (bh // H) * Hkv + (bh % H) // group

    # ---- dq: Q block resident, K/V blocks stream (ki innermost).
    def q_index(bh, qi, ki):
        return (bh, qi, 0)

    def kv_index_dq(bh, qi, ki):
        if causal:
            ki = jnp.where(
                _visible(qi, block_q, ki, block_k, q_offset), ki, 0)
        return (kv_row(bh), ki, 0)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          q_offset=q_offset),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, block_k, D), kv_index_dq),
            pl.BlockSpec((1, block_k, D), kv_index_dq),
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, block_q, LANES), q_index),
            pl.BlockSpec((1, block_q, LANES), q_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), q_index),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        interpret=interpret,
        **params,
    )(qr, kr, vr, dor, lser, dir_).reshape(B, H, Sq, D)

    # ---- dk/dv: K/V block resident, Q blocks stream (qi innermost).
    # Emitted per *query* head; group-summed below when GQA.
    def kv_index(bh, ki, qi):
        return (kv_row(bh), ki, 0)

    def q_index_dkv(bh, ki, qi):
        if causal:
            # Skipped q blocks (above diagonal) redirect their DMA to the
            # next diagonal block to avoid wasted bandwidth.
            qi = jnp.where(
                _visible(qi, block_q, ki, block_k, q_offset), qi,
                jnp.minimum((ki * block_k) // block_q, nq - 1))
        return (bh, qi, 0)

    def dkv_index(bh, ki, qi):
        return (bh, ki, 0)

    dkv_dtype = jnp.float32 if group > 1 else q.dtype
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          q_offset=q_offset),
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_index_dkv),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_q, D), q_index_dkv),
            pl.BlockSpec((1, block_q, LANES), q_index_dkv),
            pl.BlockSpec((1, block_q, LANES), q_index_dkv),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), dkv_index),
            pl.BlockSpec((1, block_k, D), dkv_index),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), dkv_dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), dkv_dtype),
        ],
        scratch_shapes=[
            _vmem((block_k, D), jnp.float32),
            _vmem((block_k, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(qr, kr, vr, dor, lser, dir_)

    dk = dk.reshape(B, H, Sk, D)
    dv = dv.reshape(B, H, Sk, D)
    if group > 1:
        dk = dk.reshape(B, Hkv, group, Sk, D).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(B, Hkv, group, Sk, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------- wrapper

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, q_offset, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            q_offset, interpret, need_lse=False)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, q_offset, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              q_offset, interpret, need_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, q_offset, interpret, res,
               dout):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, dout, causal, scale, block_q,
                           block_k, q_offset, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 512,
                    block_k: int = 512, q_offset: int = 0,
                    interpret: bool = False):
    """Pallas flash attention (fwd + bwd kernels) with custom VJP.
    q: [B, H, Sq, D]; k/v: [B, Hkv, Sk, D]."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, scale, block_q, block_k, q_offset,
                  interpret)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
              impl: Optional[str] = None):
    """Dispatching entry point: pallas flash on TPU, reference elsewhere."""
    if impl == "reference" or (impl is None and not _on_tpu()):
        return reference_attention(q, k, v, causal=causal, scale=scale)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=True)
    return flash_attention(q, k, v, causal=causal, scale=scale)
