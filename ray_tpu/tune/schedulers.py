"""Trial schedulers: FIFO, ASHA, median-stopping.

Reference analog: python/ray/tune/schedulers/ (async_hyperband.py
ASHAScheduler, median_stopping_rule.py).  The controller calls
``on_result(trial_id, step, value)`` for every intermediate report; CONTINUE
or STOP comes back.
"""

from __future__ import annotations

import collections
import math
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, step: int, value: float) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rungs at grace_period * reduction_factor**k; a trial reaching a rung
    stops unless its metric is in the top 1/reduction_factor of completed
    rung entries.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = collections.defaultdict(list)

    def _rung_levels(self) -> List[int]:
        levels = []
        t = self.grace
        while t < self.max_t:
            levels.append(t)
            t *= self.rf
        return levels

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        if self.mode == "max":
            value = -value
        for rung in self._rung_levels():
            if step == rung:
                peers = self._rungs[rung]
                peers.append(value)
                k = max(1, len(peers) // self.rf)
                cutoff = sorted(peers)[k - 1]
                if value > cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose running-best is worse than the median of other
    trials' running means (reference: median_stopping_rule.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, step: int, value: float) -> str:
        if self.mode == "max":
            value = -value
        self._history[trial_id].append(value)
        if step < self.grace:
            return CONTINUE
        others = [sum(v) / len(v) for t, v in self._history.items()
                  if t != trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        others_sorted = sorted(others)
        median = others_sorted[len(others_sorted) // 2]
        best = min(self._history[trial_id])
        return STOP if best > median else CONTINUE
