"""Node plane: worker pool + per-node dispatch (the raylet equivalent).

The reference's raylet owns the WorkerPool (reference:
src/ray/raylet/worker_pool.h:283 — process spawning, idle pools, prestart),
local dispatch with resource pinning (local_lease_manager.h:61) and the
node's object store.  Here NodeManager plays that role for one host: it
spawns Python worker processes (multiprocessing ``spawn`` so jax state never
leaks across fork), keeps an idle pool, pins TPU chips to granted tasks via
``TPU_VISIBLE_CHIPS``-style env isolation (reference:
python/ray/_private/accelerators/tpu.py set_current_process_visible_accelerator_ids),
and runs one receiver thread per worker that routes TaskDone / nested
submissions / get requests back into the Runtime.

Chaos hooks are built into the send path from day one (reference:
src/ray/rpc/rpc_chaos.cc:33 RAY_testing_rpc_failure): configured drop
probabilities and injected delays apply to every message class.
"""

from __future__ import annotations

import os
import random
import socket
import subprocess
import sys
import tempfile
import threading
import time
from select import select as _select
from dataclasses import dataclass, field
from multiprocessing.connection import Listener
from typing import Any, Callable, Dict, List, Optional, Set

from . import wire
from .config import Config
from .controller import NodeInfo
from .ids import ActorID, NodeID, TaskID, WorkerID
from .object_store import NativeArenaStore, create_store
from .protocol import (ActorStateMsg, AllocReply, AllocRequest,
                       BorrowRetained, ContainedRefs, GetRequest,
                       KillWorker, ProfileReply, ProfileRequest,
                       PutFromWorker, ReadDone, RpcCall, RunTask,
                       SealObject, StackDumpReply, StackDumpRequest,
                       SubmitFromWorker, TaskDone, TaskSpec, WaitRequest,
                       WorkerReady)
from .resources import ResourceSet, TPU
from ..util import telemetry

IDLE = "idle"
BUSY = "busy"
DEAD = "dead"

_WIRE_NAMES = {wire.RUN_TASK: "RunTask", wire.TASK_DONE: "TaskDone"}


def _wire_msg_name(msg) -> str:
    """Message-class name for chaos config matching; wire tuples map back
    to the dataclass names so existing testing_rpc_failure specs apply."""
    if type(msg) is tuple:
        return _WIRE_NAMES.get(msg[0], str(msg[0]))
    return type(msg).__name__


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    proc: Any
    conn: Any
    state: str = IDLE
    actor_id: Optional[ActorID] = None
    # Chip-holding workers are dedicated: they are killed after their task
    # and their chips return to the pool only when the process death is
    # observed (libtpu releases device locks at exit).  Env-only workers
    # are pooled per env signature instead.
    dedicated: bool = False
    env_key: str = ""
    death_reason: str = ""
    # fn_ids whose blobs this worker has already received — later specs
    # ship without the blob (reference: function-table export-once).
    seen_fns: Set[bytes] = field(default_factory=set)
    # Registration-timeout Timer; cancelled the moment the worker
    # registers (otherwise one timer thread per spawn idles out the
    # full worker_register_timeout_s — a leak the sanitizer flags).
    register_watchdog: Optional[Any] = None
    running: Set[TaskID] = field(default_factory=set)
    # task_id -> (start_monotonic, retriable) for the OOM kill policy.
    task_meta: Dict[TaskID, Any] = field(default_factory=dict)
    # Direct actor calls in flight (no running/task_meta entries): count +
    # oldest-start, enough for the OOM victim policy to see the worker.
    direct_inflight: int = 0
    direct_since: float = 0.0
    reader: Optional[threading.Thread] = None
    ready: threading.Event = field(default_factory=threading.Event)
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    assigned_chips: Dict[TaskID, List[int]] = field(default_factory=dict)
    # Messages queued before the worker registered (async spawn): flushed
    # in order by the acceptor as soon as the connection lands.
    pending_msgs: List[Any] = field(default_factory=list)
    # Arena-store pin bookkeeping (native store only; see object_store.py):
    # args pinned for in-flight tasks, pins from outstanding GetReplies, pins
    # promoted to worker lifetime (actor-retained views), unsealed allocs.
    arg_pins: Dict[TaskID, List[bytes]] = field(default_factory=dict)
    get_pins: Dict[int, List[bytes]] = field(default_factory=dict)
    lifetime_pins: List[bytes] = field(default_factory=list)
    unsealed: Set[Any] = field(default_factory=set)


class NodeManager:
    def __init__(self, node_info: NodeInfo, runtime, num_tpu_chips: int = 0):
        self.info = node_info
        self.runtime = runtime  # driver Runtime; provides message handlers
        self.store = create_store()
        self._native_store = isinstance(self.store, NativeArenaStore)
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: Dict[str, List[WorkerID]] = {}
        self._lock = threading.RLock()
        self._chip_pool: List[int] = list(range(num_tpu_chips))
        self._closed = False
        # (sys.path ships per SPAWN, not frozen here: a driver that
        # appends an import dir after init — compiled protos, generated
        # code — must still resolve in later workers.)
        # Workers are spawned as fresh interpreters that dial back in
        # (reference: worker_pool.h StartWorkerProcess + raylet socket
        # registration) — no fork, no __main__ re-import, no jax inheritance.
        self._sock_path = os.path.join(
            tempfile.mkdtemp(prefix="ray_tpu_"), "node.sock")
        self._authkey = os.urandom(16)
        # Direct worker->worker call channels (direct.py): the token all
        # listeners/callers authenticate with, and the host workers bind
        # their listeners on.  Cluster setups overwrite these with the
        # cluster token + advertise host so channels work across nodes.
        self.direct_token: bytes = self._authkey
        self.direct_host: str = "127.0.0.1"
        self._listener = Listener(self._sock_path, "AF_UNIX",
                                  authkey=self._authkey)
        # One multiplexed poller over every worker connection instead of a
        # reader thread per worker (reference: asio io_service event loops)
        # — N reader threads ping-ponging the GIL with the dispatch thread
        # measurably halved task throughput at 8+ workers.
        self._poll_conns: Dict[Any, WorkerHandle] = {}
        self._conns_version = 0
        self._poll_wake_r, self._poll_wake_w = os.pipe()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="node-poller", daemon=True)
        self._poller.start()
        # Outgoing messages ride one sender thread: callers enqueue (cheap)
        # and move on; the sender coalesces everything queued per worker
        # into a single list frame — one pickle, one write — so a burst of
        # dispatches costs O(batches) syscalls instead of O(tasks)
        # (reference: the C++ core worker's pooled gRPC streams amortize
        # the same way).
        import collections
        self._outbox: Any = collections.deque()
        self._out_ev = threading.Event()
        self._sender = threading.Thread(target=self._send_loop,
                                        name="node-sender", daemon=True)
        self._sender.start()
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          name="node-acceptor", daemon=True)
        self._acceptor.start()
        # chaos config parsed once
        self._drop_probs: Dict[str, float] = {}
        spec = Config.get("testing_rpc_failure")
        if spec:
            for part in spec.split(","):
                if "=" in part:
                    m, p = part.split("=")
                    self._drop_probs[m.strip()] = float(p)
        # OOM protection (reference: raylet MemoryMonitor + worker-killing
        # policy); no-op unless memory_monitor_refresh_ms > 0.
        from .memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(self)
        self.memory_monitor.start()
        # Worker resource isolation (reference: cgroup2/cgroup_manager.h);
        # no-op unless enable_resource_isolation.
        from .cgroup import CgroupManager
        self.cgroup = CgroupManager()

    # -- worker lifecycle ---------------------------------------------------

    def _accept_loop(self) -> None:
        # Safe bare reads: _closed is a monotonic shutdown latch; the
        # worst a stale False costs is one extra loop iteration.
        while not self._closed:  # ray-tpu: noqa[RT401]
            try:
                conn = self._listener.accept()
            except Exception:  # noqa: BLE001
                # Covers OSError/EOFError AND AuthenticationError: a worker
                # SIGKILLed mid-handshake (OOM kill, ray_tpu.kill, chaos)
                # leaves a half-written challenge response — the accept
                # loop must survive it or no worker can ever register
                # again.
                if self._closed:
                    return
                continue
            if self._closed:
                try:
                    conn.close()
                except Exception:  # ray-tpu: noqa[RT202] — teardown close
                    pass
                return
            try:
                hello: WorkerReady = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            with self._lock:
                handle = self._workers.get(hello.worker_id)
            if handle is None:
                conn.close()
                continue
            # Install the connection and flush messages dispatched while
            # the worker was still booting (async spawn), preserving order
            # against concurrent _send calls via the send lock.
            with handle.send_lock:
                handle.conn = conn
                for m in handle.pending_msgs:
                    try:
                        conn.send(m)
                    except (BrokenPipeError, OSError):
                        break
                handle.pending_msgs.clear()
            handle.ready.set()
            self._cancel_register_watchdog(handle)
            with self._lock:
                self._poll_conns[conn] = handle
                self._conns_version += 1
            self._wake_poller()

    def _wake_poller(self) -> None:
        try:
            os.write(self._poll_wake_w, b"x")
        except OSError:
            pass

    def _poll_loop(self) -> None:
        """Single event loop over all worker pipes (reference: the
        raylet's asio loop servicing every worker connection).

        The selector is persistent — connections register once when they
        land and unregister at death — because rebuilding a selector per
        poll (multiprocessing.connection.wait's behavior) re-registered
        every fd every iteration and showed up directly in dispatch
        profiles.  After a conn turns readable, every already-buffered
        frame is drained before re-polling.

        Known tradeoff: recv() after readability is frame-blocking, so a
        worker stopped mid-frame (SIGSTOP) would stall the loop — the
        per-worker-thread model confined that to one worker but cost ~2x
        task throughput in GIL ping-pong.  True non-blocking framing
        belongs in the native transport when this pipe moves to C++.
        """
        import selectors
        sel = selectors.DefaultSelector()
        sel.register(self._poll_wake_r, selectors.EVENT_READ, None)
        registered: Dict[Any, Any] = {}  # conn -> handle
        seen_version = -1
        while not self._closed:
            with self._lock:
                version = self._conns_version
                current = dict(self._poll_conns) if version != seen_version \
                    else None
            if current is not None:
                seen_version = version
                for c in list(registered):
                    if c not in current:
                        registered.pop(c)
                        try:
                            sel.unregister(c)
                        except (KeyError, ValueError, OSError):
                            pass
                for c, h in current.items():
                    if c not in registered:
                        try:
                            sel.register(c, selectors.EVENT_READ, h)
                        except (KeyError, ValueError, OSError):
                            # fd already dead (worker crashed between accept
                            # and registration): run the death path now —
                            # no EOF event will ever arrive for this conn.
                            with self._lock:
                                self._poll_conns.pop(c, None)
                                self._conns_version += 1
                            seen_version = -1
                            self._on_worker_death(h)
                            continue
                        registered[c] = h
            try:
                events = sel.select(timeout=1.0)
            except OSError:
                events = []
            for key, _mask in events:
                c = key.fileobj
                if c is self._poll_wake_r:
                    try:
                        os.read(self._poll_wake_r, 4096)
                    except OSError:
                        pass
                    continue
                handle = key.data
                # Drain every buffered frame before re-polling (cap keeps
                # one chatty worker from starving the rest).
                for _ in range(64):
                    try:
                        frame = c.recv()
                    except (EOFError, OSError):
                        with self._lock:
                            self._poll_conns.pop(c, None)
                            self._conns_version += 1
                        registered.pop(c, None)
                        try:
                            sel.unregister(c)
                        except (KeyError, ValueError, OSError):
                            pass
                        self._on_worker_death(handle)
                        break
                    if type(frame) is list:
                        # Per-message isolation: one bad message must not
                        # drop the rest of its batch (a lost TaskDone
                        # hangs the caller forever).
                        for m in frame:
                            try:
                                self._handle_msg(handle, m)
                            except Exception:
                                import traceback
                                traceback.print_exc()
                    else:
                        try:
                            self._handle_msg(handle, frame)
                        except Exception:
                            import traceback
                            traceback.print_exc()
                    try:
                        # Raw select probe: Connection.poll(0) builds a
                        # fresh selector object per call (~15us); this is
                        # one cheap syscall.
                        readable, _, _ = _select([c], [], [], 0)
                    except (OSError, ValueError):
                        break
                    if not readable:
                        break
        sel.close()

    def _send_loop(self) -> None:
        """Drain the outbox, grouping queued messages per worker into one
        list frame (single pickle + single write).  FIFO order within a
        worker is preserved — actor-method ordering and the
        creation-before-methods invariant depend on it."""
        outbox, ev = self._outbox, self._out_ev
        while True:
            ev.wait()
            ev.clear()
            if self._closed:
                # Checked after clear(): a close racing the wakeup must not
                # have its set() erased and leave join() to time out.
                return
            groups: List[tuple] = []  # (handle, [msgs]) in first-seen order
            index: Dict[int, int] = {}
            while True:
                try:
                    handle, msg = outbox.popleft()
                except IndexError:
                    break
                i = index.get(id(handle))
                if i is None:
                    index[id(handle)] = len(groups)
                    groups.append((handle, [msg]))
                else:
                    groups[i][1].append(msg)
            for handle, msgs in groups:
                try:
                    with handle.send_lock:
                        if handle.conn is None:
                            # Worker still booting (async spawn): queue in
                            # order; the acceptor flushes on registration.
                            handle.pending_msgs.extend(msgs)
                            continue
                        handle.conn.send(msgs if len(msgs) > 1 else msgs[0])
                except (BrokenPipeError, OSError):
                    pass  # poll loop will notice the death
                except Exception:
                    # e.g. an unpicklable field: isolate the poisonous
                    # message so the rest of the batch (and this thread!)
                    # survives — a dead sender wedges all outbound traffic.
                    self._send_individually(handle, msgs)

    def _send_individually(self, handle: WorkerHandle, msgs: List) -> None:
        for m in msgs:
            try:
                with handle.send_lock:
                    if handle.conn is None:
                        handle.pending_msgs.append(m)
                    else:
                        handle.conn.send(m)
            except (BrokenPipeError, OSError):
                return
            except Exception:
                import traceback
                traceback.print_exc()
                # A RunTask that can't serialize must fail its task, not
                # silently hang the caller — and the node-side worker/pin
                # state must unwind as if the task had died.
                if type(m) is tuple and m[0] == wire.RUN_TASK:
                    ids = (m[1], m[6])
                elif isinstance(m, RunTask):
                    ids = (m.spec.task_id.binary(),
                           [r.binary() for r in m.spec.return_ids])
                else:
                    continue
                try:
                    self._abort_sent_task(handle, TaskID(ids[0]))
                except ValueError:
                    pass
                self.runtime.fail_task_bytes(
                    ids[0], ids[1], "task message failed to serialize")

    def _abort_sent_task(self, handle: WorkerHandle, task_id: TaskID) -> None:
        """Unwind node-side state for a task whose RunTask never made it to
        the worker (sender-side failure): drop running/meta, release arg
        pins, return the worker to the pool."""
        handle.running.discard(task_id)
        handle.task_meta.pop(task_id, None)
        if self._native_store:
            for k in handle.arg_pins.pop(task_id, []):
                self.store.unpin_key(k)
        if handle.actor_id is None and not handle.dedicated:
            self._release_worker(handle)

    def _spawn_worker(self, env: Optional[Dict[str, str]] = None) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env.update({
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            "RAY_TPU_JOB_ID": self.runtime.job_id.hex(),
            "RAY_TPU_NODE_SOCK": self._sock_path,
            "RAY_TPU_AUTHKEY": self._authkey.hex(),
            "RAY_TPU_DIRECT_TOKEN": self.direct_token.hex(),
            "RAY_TPU_DIRECT_HOST": self.direct_host,
            "RAY_TPU_CONFIG_BLOB": Config.blob(),
            # Driver sys.path travels to workers so functions pickled
            # by reference (importable modules, incl. test files) resolve
            # (reference: runtime-env working_dir/py_modules propagation).
            # Computed per spawn — exists (not isdir): zip/egg/pyz
            # entries are importable too.
            "RAY_TPU_SYS_PATH": os.pathsep.join(
                p for p in sys.path if p and os.path.exists(p)),
            # Arena segment name: workers write large results straight into
            # the node's C++ store (empty = fall back to per-object segments).
            "RAY_TPU_ARENA_SEG":
                self.store.segment_name if self._native_store else "",
        })
        # Per-worker log files in the session dir, tailed back to the
        # driver by the log monitor (reference: workers log to
        # /tmp/ray/session_*/logs, republished by log_monitor.py:116).
        popen_kw: Dict[str, Any] = {}
        logs_dir = getattr(self.runtime, "session_logs_dir", None)
        if logs_dir and Config.get("redirect_worker_logs"):
            tag = f"worker-{worker_id.hex()[:8]}"
            out = None
            try:
                out = open(os.path.join(logs_dir, tag + ".out"), "ab")
                err = open(os.path.join(logs_dir, tag + ".err"), "ab")
                popen_kw = {"stdout": out, "stderr": err}
            except OSError:
                if out is not None:
                    out.close()
                popen_kw = {}
        child_env.update(self.cgroup.spawn_env())
        # pip runtime envs run the worker under their venv interpreter
        # (reference: pip plugin's python_interpreter override).
        python = child_env.pop("RAY_TPU_PYTHON", sys.executable)
        try:
            proc = subprocess.Popen(
                [python, "-m", "ray_tpu._private.worker_main"],
                env=child_env, cwd=os.getcwd(), **popen_kw)
        finally:
            for f in popen_kw.values():
                f.close()  # child holds the fd; parent must not leak it
        self.cgroup.add_process(proc.pid)
        handle = WorkerHandle(worker_id, proc, None)
        with self._lock:
            self._workers[worker_id] = handle
        # Async spawn: dispatches queue in pending_msgs and the task starts
        # the moment the worker registers — the dispatch thread never
        # blocks on interpreter boot.  A watchdog converts a never-
        # registering worker into the normal death path (queued tasks
        # retry elsewhere).
        def _watchdog(h=handle):
            if not h.ready.is_set():
                self._kill_and_reap(h)
        t = threading.Timer(Config.get("worker_register_timeout_s"),
                            _watchdog)
        t.daemon = True
        with self._lock:
            if self._closed:
                # shutdown()'s cancel sweep already ran (or is running):
                # starting the timer now would leave it ticking against
                # a torn-down manager for the full register timeout.
                return handle
            handle.register_watchdog = t
        t.start()
        return handle

    def _cancel_register_watchdog(self, handle: WorkerHandle) -> None:
        t, handle.register_watchdog = handle.register_watchdog, None
        if t is not None:
            t.cancel()

    def _kill_and_reap(self, handle: WorkerHandle) -> None:
        """SIGKILL a worker and guarantee its death handler runs.

        A worker killed before (or during) registration produces no pipe
        EOF for the poller, so reap explicitly: wait for the process, give
        the EOF path a moment, then run the (idempotent) death handler if
        it hasn't fired.  Shared by OOM kills, forced actor kills and the
        registration watchdog so the three paths cannot drift.
        """
        try:
            if handle.proc.poll() is None:
                handle.proc.kill()
        except Exception as e:
            telemetry.note_swallowed("node.kill_worker", e)

        def _reap(h=handle):
            try:
                h.proc.wait(timeout=60)
            except Exception as e:
                telemetry.note_swallowed("node.reap_worker", e)
            time.sleep(1.0)
            if h.state != DEAD:
                self._on_worker_death(h)
        from . import sanitizer
        sanitizer.spawn(_reap, name="worker-reap")

    def _acquire_worker(self, env_key: str = "",
                        env: Optional[Dict[str, str]] = None) -> WorkerHandle:
        """Reuse an idle worker with a matching spawn env, else spawn.

        Workers are pooled per env signature: boot-time env (jax platform,
        flags) can't change after spawn, but identical-env tasks reuse the
        same interpreters.
        """
        with self._lock:
            bucket = self._idle.get(env_key, [])
            while bucket:
                wid = bucket.pop()
                h = self._workers.get(wid)
                if h is not None and h.state == IDLE:
                    h.state = BUSY
                    return h
        h = self._spawn_worker(env=env)
        h.state = BUSY
        h.env_key = env_key
        return h

    def _release_worker(self, handle: WorkerHandle) -> None:
        with self._lock:
            if handle.state == DEAD or handle.actor_id is not None:
                return
            handle.state = IDLE
            self._idle.setdefault(handle.env_key, []).append(
                handle.worker_id)

    # -- dispatch -----------------------------------------------------------

    def dispatch_task(self, spec: TaskSpec,
                      resolved_args, resolved_kwargs,
                      target_worker: Optional[WorkerID] = None,
                      _retry_deadline: Optional[float] = None,
                      _env_bg: bool = False) -> None:
        """Send a fully-resolved task to a worker (lease grant + push)."""
        env_vars: Dict[str, str] = dict(
            spec.runtime_env.get("env_vars", {})) if spec.runtime_env else {}
        if spec.runtime_env and (spec.runtime_env.get("working_dir")
                                 or spec.runtime_env.get("py_modules")
                                 or spec.runtime_env.get("pip")):
            from .runtime_env import pip_env_ready
            if not _env_bg and not pip_env_ready(spec.runtime_env):
                # Cold pip env: venv creation + pip install can take
                # minutes — building it inline would stall the single
                # dispatch thread (and with it every other task in the
                # cluster).  Re-enter on a builder thread instead
                # (reference: runtime-env agent builds envs off the
                # raylet's dispatch path).
                def _bg():
                    try:
                        self.dispatch_task(spec, resolved_args,
                                           resolved_kwargs, target_worker,
                                           _retry_deadline, _env_bg=True)
                    except Exception as e:  # noqa: BLE001
                        self.runtime.scheduler.release(
                            self.info.node_id, spec.resources,
                            spec.placement_group, spec.bundle_index)
                        self.runtime.on_dispatch_failed(spec, repr(e))
                from . import sanitizer
                sanitizer.spawn(_bg, name="runtime-env-build")
                return
            # Extract content-addressed packages into the node session dir;
            # workers apply them at boot (reference: runtime-env agent
            # GetOrCreateRuntimeEnv before the lease grant).
            from .runtime_env import node_setup_env_vars
            env_vars.update(node_setup_env_vars(spec.runtime_env))
        # TPU chip pinning: integral chip grants get exclusive visibility via
        # spawn-time env (libtpu/jax read it at process boot).
        n_chips = int(spec.resources.get(TPU))
        grant: List[int] = []
        if n_chips > 0 and target_worker is None:
            with self._lock:
                if len(self._chip_pool) >= n_chips:
                    grant = self._chip_pool[:n_chips]
                    del self._chip_pool[:n_chips]
            if not grant:
                # Chips freed in the scheduler but physically still held by
                # a dying worker (libtpu locks release at process exit):
                # retry until the death handler returns them.
                if _retry_deadline is None:
                    _retry_deadline = time.monotonic() + \
                        Config.get("lease_timeout_s")
                if time.monotonic() > _retry_deadline:
                    self.runtime.scheduler.release(
                        self.info.node_id, spec.resources,
                        spec.placement_group, spec.bundle_index)
                    self.runtime.on_dispatch_failed(
                        spec, f"timed out waiting for {n_chips} TPU chips")
                    return

                def _retry():
                    try:
                        self.dispatch_task(spec, resolved_args,
                                           resolved_kwargs, target_worker,
                                           _retry_deadline)
                    except Exception as e:  # noqa: BLE001
                        self.runtime.scheduler.release(
                            self.info.node_id, spec.resources,
                            spec.placement_group, spec.bundle_index)
                        self.runtime.on_dispatch_failed(spec, repr(e))
                t = threading.Timer(0.05, _retry)
                t.daemon = True
                t.start()
                return
            # Always overwrite: a retried task must see its fresh grant,
            # not the first attempt's chips.  The pinning env comes from
            # the accelerator plugin (accelerators/accelerator.py); the
            # config override supports tests faking the env name.
            env_name = Config.get("visible_accelerator_env")
            from ..accelerators.accelerator import get_accelerator
            mgr = get_accelerator("TPU")
            if mgr is not None and env_name == "TPU_VISIBLE_CHIPS":
                env_vars.update(mgr.visibility_env(grant))
            else:
                env_vars[env_name] = ",".join(str(c) for c in grant)
        if target_worker is not None:
            with self._lock:
                handle = self._workers.get(target_worker)
            if handle is None or handle.state == DEAD:
                self.runtime.on_dispatch_failed(spec, "target worker dead")
                return
        else:
            env_key = ""
            if env_vars:
                env_key = repr(sorted(env_vars.items()))  # boot-env identity
            try:
                if grant:
                    # Chip-holding workers are never pooled: the process
                    # must die before its chips are reusable.
                    handle = self._spawn_worker(env=env_vars)
                    handle.state = BUSY
                    handle.dedicated = True
                else:
                    handle = self._acquire_worker(env_key, env_vars or None)
            except Exception:
                if grant:
                    with self._lock:
                        self._chip_pool.extend(grant)
                # Propagate: the scheduler's dispatch-error path releases
                # the booked resources and fails the task.
                raise
        if spec.create_actor_id is not None:
            handle.actor_id = spec.create_actor_id
        if grant:
            died = False
            with self._lock:
                if handle.state == DEAD or \
                        handle.worker_id not in self._workers:
                    # Worker died between spawn and chip assignment: the
                    # death handler saw no assigned chips, so return them
                    # here and fail the task cleanly.
                    self._chip_pool.extend(grant)
                    died = True
                else:
                    handle.assigned_chips[spec.task_id] = grant
            if died:
                # Fail OUTSIDE the node lock (RT404): the dispatch-failed
                # path re-enters scheduler/runtime state and must not
                # hold this lock across that work.
                self.runtime.on_dispatch_failed(
                    spec, "worker died before chip assignment")
                return
        if env_vars:
            # Never mutate the caller's spec (retries rebuild from it).
            import copy as _copy
            spec = _copy.copy(spec)
            spec.runtime_env = dict(spec.runtime_env or {}, env_vars=env_vars)
        fn_blob = spec.fn_blob
        if spec.fn_id is not None and fn_blob is not None:
            if spec.fn_id in handle.seen_fns:
                # Worker already holds this function: ship the frame without
                # the blob (workers fall back to a ctl fetch on a miss).
                # The strip happens at encode time — the driver-side spec
                # (lineage, retries) keeps its blob.
                fn_blob = None
            else:
                handle.seen_fns.add(spec.fn_id)
        if self._native_store:
            # Refresh + pin arena-resident args so their offsets stay valid
            # for the task's lifetime (plasma client-pin semantics).
            ok, resolved_args, resolved_kwargs = self._pin_args(
                handle, spec, resolved_args, resolved_kwargs)
            if not ok:
                return
        handle.running.add(spec.task_id)
        handle.task_meta[spec.task_id] = (
            time.monotonic(),
            spec.create_actor_id is None and spec.actor_id is None
            and spec.retry_count < spec.max_retries)
        self.runtime.note_task_running(spec.task_id, self.info.node_id,
                                       handle.worker_id)
        if spec.create_actor_id is None:
            # Hot path: compact tuple frame (no dataclass pickling, no
            # double-shipped arg payloads) — see wire.py.
            self._send(handle, wire.encode_run_task(
                spec, resolved_args, resolved_kwargs, fn_blob))
        else:
            if fn_blob is not spec.fn_blob:
                import copy as _copy
                spec = _copy.copy(spec)
                spec.fn_blob = fn_blob
            self._send(handle, RunTask(spec, resolved_args, resolved_kwargs))
        if spec.create_actor_id is not None:
            # Bind only after the creation message is on the wire so queued
            # method calls can never overtake __init__ on the worker pipe.
            self.runtime.bind_actor_worker(
                spec.create_actor_id, self.info.node_id, handle.worker_id)

    def dispatch_actor_task(self, spec: TaskSpec, resolved_args,
                            resolved_kwargs, worker_id: WorkerID) -> None:
        """Slim dispatch for actor method calls: the worker is known and
        bound, there is no env/chip/strategy work to do — just pin, track
        and ship (reference: direct actor submission over the persistent
        gRPC stream, actor_task_submitter.h)."""
        with self._lock:
            handle = self._workers.get(worker_id)
        if handle is None or handle.state == DEAD:
            self.runtime.on_dispatch_failed(spec, "target worker dead")
            return
        if self._native_store:
            ok, resolved_args, resolved_kwargs = self._pin_args(
                handle, spec, resolved_args, resolved_kwargs)
            if not ok:
                return
        handle.running.add(spec.task_id)
        handle.task_meta[spec.task_id] = (time.monotonic(), False)
        self.runtime.note_task_running(spec.task_id, self.info.node_id,
                                       handle.worker_id)
        self._send(handle, wire.encode_run_task(
            spec, resolved_args, resolved_kwargs, spec.fn_blob))

    @staticmethod
    def _pipeline_eligible(h, max_depth: int) -> bool:
        """Can this pooled worker take a queued-ahead (pipelined) task?
        Single definition shared by the has_pipeline_room precheck and
        the dispatch_pipelined selection loop — they must never drift."""
        return (h.state in (BUSY, IDLE) and h.actor_id is None
                and not h.dedicated and h.env_key == ""
                and h.ready.is_set() and len(h.running) < max_depth)

    def has_pipeline_room(self, max_depth: int = 4) -> bool:
        """Cheap precheck for dispatch_pipelined: is any pooled worker
        below the queue-ahead depth cap?  Lets the topup loop skip the
        resolve/queue/requeue cycle when the pool is full."""
        with self._lock:
            return any(self._pipeline_eligible(h, max_depth)
                       for h in self._workers.values())

    def dispatch_pipelined(self, spec: TaskSpec, resolved_args,
                           resolved_kwargs, max_depth: int = 4) -> bool:
        """Queue a plain task ahead on a busy pooled worker (pipelined
        submission, reference: the C++ submitter's
        max_tasks_in_flight_per_worker).  The task holds no resource
        booking — per-worker execution is serial, so real parallelism
        stays bounded by booked capacity; queueing ahead only hides the
        TaskDone -> dispatch round-trip latency.  Returns False if no
        worker has pipeline room."""
        with self._lock:
            best = None
            best_depth = max_depth
            for h in self._workers.values():
                if self._pipeline_eligible(h, best_depth):
                    best = h
                    best_depth = len(h.running)
            if best is None:
                return False
            handle = best
            claimed_idle = handle.state == IDLE
            if claimed_idle:
                # Claim it like _acquire_worker would (a worker released
                # by lease reuse an instant ago, possibly with queued
                # pipeline work).
                handle.state = BUSY
                bucket = self._idle.get(handle.env_key)
                if bucket and handle.worker_id in bucket:
                    bucket.remove(handle.worker_id)
        if self._native_store:
            ok, resolved_args, resolved_kwargs = self._pin_args(
                handle, spec, resolved_args, resolved_kwargs,
                release_on_fail=False)
            if not ok:
                if claimed_idle:
                    # Revert the claim or the worker is stranded BUSY with
                    # nothing running (unreachable by _acquire_worker).
                    self._release_worker(handle)
                return False
        fn_blob = spec.fn_blob
        if spec.fn_id is not None and fn_blob is not None:
            if spec.fn_id in handle.seen_fns:
                fn_blob = None
            else:
                handle.seen_fns.add(spec.fn_id)
        handle.running.add(spec.task_id)
        handle.task_meta[spec.task_id] = (
            time.monotonic(), spec.retry_count < spec.max_retries)
        self.runtime.note_task_running(spec.task_id, self.info.node_id,
                                       handle.worker_id)
        self._send(handle, wire.encode_run_task(
            spec, resolved_args, resolved_kwargs, fn_blob))
        return True

    def _pin_args(self, handle: WorkerHandle, spec: TaskSpec,
                  resolved_args, resolved_kwargs, release_on_fail=True):
        """Refresh + pin every arena descriptor among the resolved args.

        Pinning under the store lock guarantees the offsets we ship stay
        valid until the matching unpin (TaskDone for normal tasks, worker
        death for actor workers, which may retain zero-copy views in state).
        """
        pinned: List[bytes] = []
        lost_key = [None]

        def refresh(d):
            if isinstance(d, tuple) and d and d[0] == "shma":
                nd = self.store.pin_desc_by_key(d[4])
                if nd is not None:
                    pinned.append(nd[4])
                elif lost_key[0] is None:
                    lost_key[0] = d[4]
                return nd
            return d

        ok = True
        new_args = []
        for d in resolved_args:
            nd = refresh(d)
            if nd is None:
                ok = False
                break
            new_args.append(nd)
        new_kwargs = {}
        if ok:
            for k, d in resolved_kwargs.items():
                nd = refresh(d)
                if nd is None:
                    ok = False
                    break
                new_kwargs[k] = nd
        if not ok:
            for key in pinned:
                self.store.unpin_key(key)
            if not release_on_fail:
                # Pipelined attempt: no booking to release, no failure to
                # report — the caller just re-queues the task.
                return False, resolved_args, resolved_kwargs
            if handle.dedicated:
                # Chips stay in assigned_chips: they return to the pool only
                # when the process death is observed (libtpu lock release).
                self._send(handle, KillWorker("dispatch aborted"))
            elif handle.actor_id is None:
                self._release_worker(handle)
            if not spec.resources.is_empty() or spec.placement_group is not None:
                self.runtime.scheduler.release(
                    self.info.node_id, spec.resources,
                    spec.placement_group, spec.bundle_index)
            self.runtime.on_dispatch_failed(
                spec, "arena object freed while dispatching",
                lost_object_bytes=lost_key[0])
            return False, resolved_args, resolved_kwargs
        if pinned:
            handle.arg_pins[spec.task_id] = pinned
        return True, new_args, new_kwargs

    def track_get_pins(self, worker_id: WorkerID, request_id: int,
                       keys: List[bytes]) -> None:
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is not None and handle.state != DEAD:
                # Insert under the lock so _on_worker_death's pin drain
                # cannot interleave and strand these pins.
                handle.get_pins[request_id] = keys
                return
        for k in keys:
            self.store.unpin_key(k)

    def _send(self, handle: WorkerHandle, msg) -> None:
        if self._drop_probs or Config.get("testing_delay_us"):
            # Chaos hooks run on the caller (per message, pre-queue) so
            # drop/delay semantics are unchanged by sender coalescing.
            name = _wire_msg_name(msg)
            delay_us = Config.get("testing_delay_us")
            if delay_us:
                time.sleep(random.random() * delay_us / 1e6)
            p = self._drop_probs.get(name)
            if p and random.random() < p:
                return  # chaos: message dropped
        self._outbox.append((handle, msg))
        self._out_ev.set()

    def send_direct(self, worker_id: WorkerID, frame: tuple) -> bool:
        """Ship a pre-encoded direct-call frame to a bound actor worker.
        Returns False if the worker is unknown/dead (caller fails the
        refs)."""
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None or handle.state == DEAD:
                return False
            if handle.direct_inflight == 0:
                handle.direct_since = time.monotonic()
            handle.direct_inflight += 1
        self._send(handle, frame)
        return True

    def send_to_worker(self, worker_id: WorkerID, msg) -> None:
        with self._lock:
            handle = self._workers.get(worker_id)
        if handle is not None and handle.state != DEAD:
            self._send(handle, msg)

    def broadcast_stack_dump(self, dump_id: int) -> List[WorkerID]:
        """Ship a StackDumpRequest to every registered live worker;
        returns the worker ids a reply is expected from.  Workers that
        have not finished registering are skipped — their pending-message
        queue would hold the request until boot completes, stalling the
        dump on an interpreter that is not running anything yet."""
        with self._lock:
            handles = [h for h in self._workers.values()
                       if h.state != DEAD and h.ready.is_set()
                       and h.conn is not None]
        for h in handles:
            self._send(h, StackDumpRequest(dump_id))
        return [h.worker_id for h in handles]

    def broadcast_profile(self, req: ProfileRequest) -> List[WorkerID]:
        """Ship a ProfileRequest to every registered live worker (same
        ready-gating as broadcast_stack_dump: a worker still booting
        would just hold the capture open past its window); returns the
        worker ids a reply is expected from."""
        with self._lock:
            handles = [h for h in self._workers.values()
                       if h.state != DEAD and h.ready.is_set()
                       and h.conn is not None]
        for h in handles:
            self._send(h, req)
        return [h.worker_id for h in handles]

    # -- receive ------------------------------------------------------------

    def _handle_msg(self, handle: WorkerHandle, msg) -> None:
        rt = self.runtime
        if type(msg) is tuple:
            if msg[0] == wire.TASK_DONE:
                # Direct actor calls (runtime.submit_actor_direct) never
                # entered running/pin bookkeeping: route their replies
                # straight to the caller-held refs.
                if rt.on_direct_task_done(msg):
                    if handle.direct_inflight > 0:
                        handle.direct_inflight -= 1
                    return
                self._handle_msg(handle, wire.decode_task_done(msg))
                return
            raise ValueError(f"unknown wire frame tag {msg[0]!r}")
        if isinstance(msg, WorkerReady):
            handle.ready.set()
            self._cancel_register_watchdog(handle)
        elif isinstance(msg, TaskDone):
            handle.running.discard(msg.task_id)
            handle.task_meta.pop(msg.task_id, None)
            if self._native_store:
                keys = handle.arg_pins.pop(msg.task_id, [])
                if keys:
                    if handle.actor_id is not None:
                        # Actor may hold zero-copy views of its args in state;
                        # keep them pinned for the worker's lifetime.
                        handle.lifetime_pins.extend(keys)
                    else:
                        for k in keys:
                            self.store.unpin_key(k)
            # Chips NEVER return to the pool at TaskDone: libtpu holds the
            # device locks until process exit, so reuse must wait for
            # _on_worker_death (actors and dedicated task workers alike).
            is_actor_worker = handle.actor_id is not None
            if not is_actor_worker and not handle.dedicated:
                # Release BEFORE the done callback: lease-reuse dispatch
                # inside on_task_done then lands on this (hot, LIFO-first)
                # worker instead of spawning a new one.
                self._release_worker(handle)
            rt.on_task_done(msg, self.info.node_id)
            if not is_actor_worker:
                if handle.dedicated:
                    # Graceful exit request, with a hard-terminate fallback:
                    # if the KillWorker message is lost (chaos, broken pipe)
                    # the process must still die or its chips leak forever.
                    self._send(handle, KillWorker("dedicated worker done"))

                    def _ensure_dead(h=handle):
                        if h.proc.poll() is None:
                            try:
                                h.proc.terminate()
                            except Exception as e:
                                telemetry.note_swallowed(
                                    "node.ensure_dead", e)
                    t = threading.Timer(2.0, _ensure_dead)
                    t.daemon = True
                    t.start()
        elif isinstance(msg, SubmitFromWorker):
            rt.submit_spec(msg.spec)
        elif isinstance(msg, GetRequest):
            rt.on_get_request(self, msg)
        elif isinstance(msg, WaitRequest):
            rt.on_wait_request(self, msg)
        elif isinstance(msg, PutFromWorker):
            rt.on_put_from_worker(msg)
        elif isinstance(msg, ActorStateMsg):
            rt.on_actor_state(msg, self.info.node_id, handle.worker_id)
        elif isinstance(msg, AllocRequest):
            res = self.store.allocate_for_worker(msg.object_id, msg.nbytes) \
                if self._native_store else None
            if res is None:
                self._send(handle, AllocReply(msg.request_id, None))
            else:
                handle.unsealed.add(msg.object_id)
                self._send(handle, AllocReply(msg.request_id, res[0], res[1]))
        elif isinstance(msg, SealObject):
            if self._native_store:
                self.store.seal(msg.object_id)
                handle.unsealed.discard(msg.object_id)
        elif isinstance(msg, ReadDone):
            keys = handle.get_pins.pop(msg.request_id, [])
            if msg.retain:
                handle.lifetime_pins.extend(keys)
            else:
                for k in keys:
                    self.store.unpin_key(k)
        elif isinstance(msg, BorrowRetained):
            for oid in msg.object_ids:
                rt.mark_escaped(oid)
        elif isinstance(msg, ContainedRefs):
            rt.note_contained(msg.outer, msg.inner)
        elif isinstance(msg, StackDumpReply):
            rt.on_stack_reply(msg, self.info.node_id)
        elif isinstance(msg, ProfileReply):
            rt.on_profile_reply(msg, self.info.node_id)
        elif isinstance(msg, RpcCall):
            rt.on_rpc_call(self, msg)

    def _on_worker_death(self, handle: WorkerHandle) -> None:
        if self._closed:
            return
        with self._lock:
            if handle.state == DEAD:
                return
            handle.state = DEAD
            self._workers.pop(handle.worker_id, None)
            # A worker killed before registering still holds a live
            # register-watchdog timer; once popped from _workers the
            # shutdown sweep can't reach it, so cancel here.
            self._cancel_register_watchdog(handle)
            bucket = self._idle.get(handle.env_key)
            if bucket and handle.worker_id in bucket:
                bucket.remove(handle.worker_id)
            for task_id, chips in handle.assigned_chips.items():
                self._chip_pool.extend(chips)
            handle.assigned_chips.clear()
            running = list(handle.running)
            pin_keys: List[bytes] = list(handle.lifetime_pins)
            for keys in handle.arg_pins.values():
                pin_keys.extend(keys)
            for keys in handle.get_pins.values():
                pin_keys.extend(keys)
            handle.arg_pins.clear()
            handle.get_pins.clear()
            handle.lifetime_pins.clear()
            unsealed = list(handle.unsealed)
            handle.unsealed.clear()
        if self._native_store:
            for k in pin_keys:
                self.store.unpin_key(k)
            for oid in unsealed:
                try:
                    self.store.delete(oid)
                except KeyError:
                    pass
        self.runtime.on_worker_died(handle.worker_id, self.info.node_id,
                                    running, handle.actor_id,
                                    reason=handle.death_reason)

    # -- OOM killing (reference: worker_killing_policy_retriable_fifo) ------

    def select_oom_victim(self) -> Optional[WorkerHandle]:
        """Pick the worker to sacrifice under memory pressure.

        Idle pooled workers first (killing them fails nothing), then busy
        workers via the retriable-LIFO policy in memory_monitor.select_victim.
        Actor workers count as non-retriable here — the node can't see how
        many restarts the actor has left, so they're protected last.
        """
        from .memory_monitor import select_victim
        with self._lock:
            for bucket in self._idle.values():
                for wid in bucket:
                    h = self._workers.get(wid)
                    if h is not None and h.state == IDLE:
                        return h
            candidates = []
            for h in self._workers.values():
                if h.state != BUSY or not (h.running or h.direct_inflight):
                    continue
                metas = [h.task_meta.get(t) for t in h.running]
                metas = [m for m in metas if m is not None]
                if not metas and not h.direct_inflight:
                    continue
                retriable = bool(metas) and all(m[1] for m in metas) \
                    and h.actor_id is None
                starts = [m[0] for m in metas]
                if h.direct_inflight:
                    starts.append(h.direct_since)
                candidates.append((h, retriable, min(starts)))
        return select_victim(candidates)

    def oom_kill_worker(self, handle: WorkerHandle, reason: str) -> None:
        handle.death_reason = f"OOM-killed: {reason}"
        with self._lock:
            bucket = self._idle.get(handle.env_key)
            if bucket and handle.worker_id in bucket:
                bucket.remove(handle.worker_id)
        self._kill_and_reap(handle)

    # -- misc ---------------------------------------------------------------

    def kill_actor_worker(self, worker_id: WorkerID, force: bool = True) -> None:
        with self._lock:
            handle = self._workers.get(worker_id)
        if handle is None:
            return
        if force and handle.proc.poll() is None:
            # SIGKILL, not SIGTERM: workers running jax install a
            # preemption-notifier SIGTERM handler that swallows the signal,
            # which would leave the "killed" actor training forever and its
            # resources never released.
            self._kill_and_reap(handle)
        else:
            self._send(handle, KillWorker("actor killed"))

    def kill_all_actor_workers(self, reason: str = "") -> None:
        """Hard-kill every bound actor worker (head restarted from its
        WAL: these actors are being revived elsewhere; a surviving stale
        worker would be a second live instance)."""
        with self._lock:
            doomed = [h.worker_id for h in self._workers.values()
                      if h.actor_id is not None]
        for wid in doomed:
            self.kill_actor_worker(wid, force=True)

    def num_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def local_view(self) -> Dict[str, Any]:
        """Load/resource snapshot for the syncer (reference:
        ResourceViewSyncMessage contents — resources + load by node)."""
        with self._lock:
            n_workers = len(self._workers)
            n_idle = sum(len(b) for b in self._idle.values())
            n_running = sum(len(h.running) for h in self._workers.values())
            free_chips = len(self._chip_pool)
        view: Dict[str, Any] = {
            "workers": n_workers,
            "idle_workers": n_idle,
            "running_tasks": n_running,
            "free_tpu_chips": free_chips,
        }
        try:
            snap = self.memory_monitor.snapshot()
            view["memory_used_bytes"] = snap.used_bytes
            view["memory_total_bytes"] = snap.total_bytes
        except Exception as e:
            telemetry.note_swallowed("node.local_view", e)
        try:
            stats = self.store.stats()
            view["store_bytes_used"] = int(stats["used_bytes"])
            # Full store sub-view for the head's memory summary, riding
            # the existing change-driven syncer.  Only idle-stable fields
            # (no ages/timestamps): an idle cluster must not resync.
            view["store"] = self._store_view(stats)
        except Exception as e:
            telemetry.note_swallowed("node.local_view", e)
        return view

    def _store_view(self, stats: Dict[str, Any],
                    top_n: int = 5) -> Dict[str, Any]:
        """Store occupancy + lifecycle summary for UpSyncView fan-out."""
        out: Dict[str, Any] = dict(stats)
        ring = getattr(self.store, "view", None)
        if ring is None:
            return out
        out["counts"] = dict(ring.counts)
        out["transfer_bytes"] = dict(ring.transfer_bytes)
        states = ring.latest_index()
        live = [st for st in states
                if st["state"] not in ("deleted", "evicted")]
        live.sort(key=lambda st: st["nbytes"], reverse=True)
        out["top_objects"] = [
            {"object_id": st["object_id"], "nbytes": st["nbytes"],
             "state": st["state"], "pins": st["pins"],
             "pinners": st["pinners"]}
            for st in live[:top_n]]
        with self._lock:
            live_tokens = {wid.hex() for wid in self._workers}
        out["leak_candidates"] = [
            {"object_id": rec["object_id"], "nbytes": rec["nbytes"],
             "reason": rec["reason"], "reads": rec["reads"],
             "pins": rec["pins"], "pinners": rec["pinners"]}
            for rec in ring.leak_candidates(live_tokens=live_tokens)[:top_n]]
        return out

    def prestart_workers(self, n: int) -> None:
        for _ in range(n):
            h = self._spawn_worker()
            with self._lock:
                self._idle.setdefault("", []).append(h.worker_id)

    def shutdown(self) -> None:
        # _closed flips under the lock: a racing _spawn_worker either
        # sees it and skips its watchdog timer, or has already published
        # handle.register_watchdog under the same lock — in which case
        # the sweep below cancels it.
        with self._lock:
            self._closed = True
            handles = list(self._workers.values())
        self.memory_monitor.stop()
        # Workers that never registered still hold a live watchdog timer.
        for h in handles:
            self._cancel_register_watchdog(h)
        self._out_ev.set()  # sender thread sees _closed and exits
        self._sender.join(timeout=3.0)
        self._wake_poller()
        # The acceptor must be OUT of accept() before the listener fd is
        # closed: a thread blocked in accept() on a closed fd can adopt
        # the fd number when the OS reuses it for a NEW runtime's listener
        # — it then steals that runtime's worker handshakes and rejects
        # them with this (stale) authkey.  Wake it with a dummy connect,
        # join, then close.  The poller gets the same treatment for its
        # wake-pipe fds (the wake write above kicks it; _closed ends it).
        if self._acceptor.is_alive():
            try:
                s = socket.socket(socket.AF_UNIX)
                s.settimeout(1.0)
                s.connect(self._sock_path)
                s.close()
            except OSError:
                pass
            self._acceptor.join(timeout=3.0)
        self._poller.join(timeout=3.0)
        try:
            self._listener.close()
        except Exception:  # ray-tpu: noqa[RT202] — best-effort teardown
            pass
        try:
            os.close(self._poll_wake_w)
            os.close(self._poll_wake_r)
        except OSError:
            pass
        with self._lock:
            handles = list(self._workers.values())
            self._workers.clear()
            self._idle.clear()
        for h in handles:
            try:
                if h.conn is not None:
                    h.conn.close()
            except Exception:  # ray-tpu: noqa[RT202] — best-effort teardown
                pass
            if h.proc.poll() is None:
                h.proc.terminate()
        for h in handles:
            try:
                h.proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                h.proc.kill()
        # Cleanup only after the workers are dead: rmdir on a cgroup with
        # live members fails EBUSY and strands the tree.
        self.cgroup.cleanup()
        self.store.shutdown()
