"""C++ user API tests: zero-copy arena reads from a compiled C++ program
(reference analog: cpp/ user API tests — here scoped to the data plane,
see cpp/README.md)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def sum_floats_bin(tmp_path_factory):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("cppbin") / "sum_floats")
    subprocess.run(
        [gxx, "-std=c++17", "-O2", "-I", os.path.join(REPO, "cpp", "include"),
         os.path.join(REPO, "cpp", "examples", "sum_floats.cc"),
         "-o", out, "-lrt"],
        check=True, capture_output=True, timeout=300)
    return out


class TestCppObjectReader:
    def test_cpp_reads_python_tensor_zero_copy(self, sum_floats_bin,
                                               ray_start):
        rt = ray_start
        arr = np.arange(100_000, dtype=np.float32)
        ref = ray_tpu.put(arr)
        # The arena descriptor: ("shma", segment, offset, nbytes, id) for
        # the native store, ("shm", name, nbytes) for the fallback.
        desc = rt.node.store.descriptor(ref.id())
        assert desc is not None
        if desc[0] == "shma":
            _, seg, off, nbytes, _ = desc
        else:
            _, seg, nbytes = desc
            off = 0
        out = subprocess.run(
            [sum_floats_bin, seg, str(off), str(nbytes)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        count, total = out.stdout.split()
        assert int(count) == 100_000
        assert float(total) == pytest.approx(float(arr.sum()), rel=1e-6)

    def test_cpp_rejects_corrupt_range(self, sum_floats_bin, ray_start):
        rt = ray_start
        ref = ray_tpu.put(np.ones(50_000, np.float32))
        desc = rt.node.store.descriptor(ref.id())
        seg = desc[1]
        # Lie about the length: the reader must fail cleanly, not crash.
        out = subprocess.run(
            [sum_floats_bin, seg, "0", str(1 << 40)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode != 0
        assert "error" in out.stderr or "segment" in out.stderr
