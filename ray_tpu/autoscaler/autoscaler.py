"""The reconciler: demand -> desired node set -> provider actions.

Reference: v2 Autoscaler (autoscaler.py:51) update loop — read demand,
run the ResourceDemandScheduler bin-packing (v2/scheduler.py:822), diff
against the instance manager's view, launch/terminate.  Simplifications
kept honest: first-fit-decreasing bin-packing over configured node types,
idle-timeout downscaling (a node with no running work past the timeout),
min/max clamps per type.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .providers import NodeProvider


@dataclass
class NodeTypeConfig:
    """reference: available_node_types entries in the autoscaler yaml."""
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 30.0
    update_interval_s: float = 1.0


class Autoscaler:
    """Reconciles cluster size against scheduler demand."""

    def __init__(self, runtime, provider: NodeProvider,
                 config: AutoscalerConfig):
        self.runtime = runtime
        self.provider = provider
        self.config = config
        # provider_id -> (node_type, launch_ts)
        self._launched: Dict[str, tuple] = {}
        # provider_id -> expected alive-worker count once this launch
        # joins (pid-less providers only; see _gang_launches fallback).
        self._expected_alive: Dict[str, int] = {}
        # node_id (runtime) -> first-seen-idle timestamp
        self._idle_since: Dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- loop ---------------------------------------------------------------

    def _loop(self) -> None:
        # Satisfy min_workers immediately.
        for name, ntc in self.config.node_types.items():
            for _ in range(ntc.min_workers):
                self._launch(name, ntc)
        while not self._stop.wait(self.config.update_interval_s):
            try:
                self._reconcile()
            except Exception:
                import traceback
                traceback.print_exc()

    def _count_by_type(self) -> Dict[str, int]:
        live = set(self.provider.non_terminated_nodes())
        counts: Dict[str, int] = {}
        for pid, (ntype, _ts) in list(self._launched.items()):
            if pid in live:
                counts[ntype] = counts.get(ntype, 0) + 1
            else:
                self._launched.pop(pid, None)
                self._expected_alive.pop(pid, None)
        return counts

    def _alive_workers(self) -> int:
        return sum(1 for n in self.runtime.controller.alive_nodes()
                   if not n.is_head)

    def _launch(self, name: str, ntc: NodeTypeConfig) -> None:
        pid = self.provider.create_node(name, ntc.resources)
        # Join expectation: the worker count this launch should bring the
        # cluster to.  Base = max(current count, any still-unmet RECENT
        # expectation) so concurrent launches stack (+1 each) and foreign
        # or pre-existing nodes — counted in the base — never satisfy it.
        # Stale expectations (launch never joined within 120s: spawn
        # failure) are dropped here, not ratcheted into the base — one
        # dead launch must not inflate every future expectation.
        now = time.monotonic()
        for p in list(self._expected_alive):
            ts = self._launched.get(p)
            if ts is None or now - ts[1] > 120.0:
                self._expected_alive.pop(p, None)
        base = max([self._alive_workers()]
                   + list(self._expected_alive.values()))
        self._expected_alive[pid] = base + 1
        self._launched[pid] = (name, now)

    def _gang_launches(self, counts: Dict[str, int]) -> Dict[str, int]:
        """Atomic multi-host gangs (pending slice/STRICT_SPREAD placement
        groups): launch the WHOLE node group or nothing (reference:
        v2/scheduler.py:822 gang resource requests).  Returns per-type
        launch counts; partial gangs are never launched."""
        gangs = self.runtime.scheduler.pending_gang_demand()
        if not gangs:
            return {}
        # Launches in flight (created by US but not yet registered with
        # the runtime, matched by OS pid): wait for them to land before
        # judging gang feasibility, or every tick would launch another
        # full gang.  Nodes that never join stop blocking after a
        # timeout (spawn failure), and foreign/manual nodes are ignored.
        joined_os_pids = set()
        for n in self.runtime.controller.alive_nodes():
            try:
                joined_os_pids.add(int(n.labels.get("os_pid", 0)))
            except (TypeError, ValueError):
                pass
        get_pid = getattr(self.provider, "node_os_pid", None)
        live = set(self.provider.non_terminated_nodes())
        now = time.monotonic()
        n_alive = self._alive_workers()
        for pid, (_ntype, ts) in self._launched.items():
            if pid not in live:
                continue
            if self._expected_alive.get(pid, 0) <= n_alive:
                # Met (or pid-matched provider): stop tracking so later
                # downscales don't inflate future launch baselines.
                self._expected_alive.pop(pid, None)
            if now - ts > 120.0:
                # Never joined: spawn failure — stop blocking AND stop
                # counting toward future launch baselines.
                self._expected_alive.pop(pid, None)
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None:
                if os_pid not in joined_os_pids:
                    return {}  # still joining; don't double-buy
            elif pid in self._expected_alive:
                # Pid-less provider (cloud/TPU-pod): the worker count
                # hasn't reached this launch's expectation yet, so the
                # node is still booting (a multi-host slice takes
                # minutes) — launching another full gang each tick would
                # over-provision entire TPU slices.
                return {}
        per_node = self.runtime.scheduler.per_node_available()
        to_launch: Dict[str, int] = {}
        for strategy, shapes, placed_nodes in gangs:
            if strategy == "STRICT_PACK":
                # One node must hold every bundle: treat as a single
                # summed shape.
                total: Dict[str, float] = {}
                for s in shapes:
                    for k, v in s.items():
                        total[k] = total.get(k, 0.0) + v
                shapes = [total]
                distinct = False
            else:
                # STRICT_SPREAD (the TPU-slice gang) and SPREAD want
                # bundle-per-node; PACK tolerates co-location but a
                # node-per-bundle launch always satisfies it.
                distinct = strategy in ("STRICT_SPREAD", "SPREAD")
            # Nodes already holding this PG's bundles can't take more of
            # its spread bundles (mirrors the scheduler's used_nodes
            # exclusion) — judging them free would deadlock a partially
            # placed gang after a node loss.
            occupied = set(placed_nodes)
            free_nodes = [dict(v) for nid, v in per_node.items()
                          if not distinct or nid not in occupied]
            needed: List[Dict[str, float]] = []
            for shape in shapes:
                placed = False
                for fn in free_nodes:
                    if all(fn.get(k, 0.0) >= v for k, v in shape.items()):
                        if distinct:
                            free_nodes.remove(fn)
                        else:
                            for k, v in shape.items():
                                fn[k] = fn.get(k, 0.0) - v
                        placed = True
                        break
                if not placed:
                    needed.append(shape)
            if not needed:
                continue  # scheduler will commit on its next retry
            # All-or-nothing: find one type fitting every missing bundle
            # with enough max_workers headroom for the full gang.
            gang_type = None
            for name, ntc in self.config.node_types.items():
                if all(all(ntc.resources.get(k, 0.0) >= v
                           for k, v in shape.items()) for shape in needed):
                    have = counts.get(name, 0) + to_launch.get(name, 0)
                    if have + len(needed) <= ntc.max_workers:
                        gang_type = name
                        break
            if gang_type is None:
                continue  # unplaceable gang stays pending (status surfaces)
            to_launch[gang_type] = to_launch.get(gang_type, 0) + len(needed)
        return to_launch

    def _reconcile(self) -> None:
        counts = self._count_by_type()
        # Gangs first: a pending slice reservation launches its whole
        # node group atomically, before flat demand claims headroom.
        gang_launch = self._gang_launches(counts)
        for name, n in gang_launch.items():
            counts[name] = counts.get(name, 0) + n
            for _ in range(n):
                self._launch(name, self.config.node_types[name])
        demand = self.runtime.scheduler.pending_demand(
            include_pg_bundles=False)

        # -- upscale: first-fit-decreasing bin-pack of unmet demand onto
        # node types (reference: v2/scheduler.py bin-packing). Capacity
        # already free in the cluster absorbs demand first (aggregate
        # pool approximation; per-node packing is the scheduler's job).
        pool = dict(self.runtime.ctl_available_resources())

        def fits_pool(shape: Dict[str, float]) -> bool:
            return all(pool.get(k, 0.0) >= v for k, v in shape.items())

        unmet: List[Dict[str, float]] = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            if fits_pool(shape):
                for k, v in shape.items():
                    pool[k] = pool.get(k, 0.0) - v
            else:
                unmet.append(shape)

        to_launch: Dict[str, int] = {}
        virtual: List[Dict[str, float]] = []
        for shape in unmet:
            placed = False
            for v in virtual:
                if all(v.get(k, 0.0) >= amt for k, amt in shape.items()):
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    placed = True
                    break
            if placed:
                continue
            for name, ntc in self.config.node_types.items():
                have = counts.get(name, 0) + to_launch.get(name, 0)
                if have >= ntc.max_workers:
                    continue
                if all(ntc.resources.get(k, 0.0) >= amt
                       for k, amt in shape.items()):
                    to_launch[name] = to_launch.get(name, 0) + 1
                    v = dict(ntc.resources)
                    for k, amt in shape.items():
                        v[k] = v.get(k, 0.0) - amt
                    virtual.append(v)
                    placed = True
                    break
            # Unplaceable on any type: stays pending (surfaced by status).
        for name, n in to_launch.items():
            for _ in range(n):
                self._launch(name, self.config.node_types[name])

        # -- downscale: terminate nodes idle past the timeout, respecting
        # per-type minimums (reference: idle node termination in v1/v2).
        if not demand:
            self._downscale_idle(counts)

    def _downscale_idle(self, counts: Dict[str, int]) -> None:
        rt = self.runtime
        now = time.monotonic()
        busy_nodes = set()
        with rt._running_lock:
            for t in rt._running.values():
                busy_nodes.add(t.node_id)
        with rt._actors_lock:
            for ast in rt._actors.values():
                if ast.node_id is not None:
                    busy_nodes.add(ast.node_id)
        # Nodes holding committed placement-group bundles are reserved
        # capacity (a TPU slice), not idle: they only become terminable
        # when the PG is removed — at which point the whole slice's nodes
        # go idle together and drain as a unit.
        from .._private.controller import PG_REMOVED
        for pg in rt.controller.placement_groups.values():
            if pg.state == PG_REMOVED:
                continue
            for b in pg.bundles:
                if b.node_id is not None:
                    busy_nodes.add(b.node_id)

        # Match provider nodes to runtime nodes by recency of launch: the
        # provider only knows pids; the runtime only knows node ids.  Idle
        # detection operates on runtime node ids; termination picks the
        # youngest idle provider node of a type over its minimum.
        alive = [n for n in rt.controller.alive_nodes() if not n.is_head]
        idle_os_pids = set()
        for n in alive:
            if n.node_id in busy_nodes:
                self._idle_since.pop(n.node_id, None)
                continue
            first = self._idle_since.setdefault(n.node_id, now)
            if now - first >= self.config.idle_timeout_s:
                try:
                    idle_os_pids.add(int(n.labels.get("os_pid", 0)))
                except (TypeError, ValueError):
                    pass
        idle_os_pids.discard(0)
        if not idle_os_pids:
            return
        # Terminate exactly the IDLE provider nodes (matched by the OS pid
        # each node reported at registration), respecting type minimums.
        get_pid = getattr(self.provider, "node_os_pid", None)
        remaining = dict(counts)
        for pid, (ntype, _ts) in list(self._launched.items()):
            if remaining.get(ntype, 0) <=                     self.config.node_types[ntype].min_workers:
                continue
            os_pid = get_pid(pid) if get_pid else None
            if os_pid is not None and os_pid in idle_os_pids:
                self.provider.terminate_node(pid)
                self._launched.pop(pid, None)
                remaining[ntype] = remaining.get(ntype, 0) - 1

    # -- introspection ------------------------------------------------------

    def status(self) -> Dict:
        return {
            "nodes_by_type": self._count_by_type(),
            "pending_demand": len(self.runtime.scheduler.pending_demand()),
        }
