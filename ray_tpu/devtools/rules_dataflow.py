"""Dataflow-backed lint rules (RT3xx): resource-lifecycle invariants.

Unlike the single-node RT1xx/RT2xx rules these run over the per-function
CFG built by :mod:`ray_tpu.devtools.dataflow` — a leak is a *path*
property (``try_pin`` on one branch, ``try_unpin`` missing on the
exception branch).  They are internal-scope: the framework's own
acquire/release pairs are the table they check.

* RT301 — resource acquired but not released on **all** paths (pins,
  bare ``lock.acquire()``, ``open()`` without ``with``/``close``,
  ``threading.Thread(...).start()`` with no reachable ``join``/tracked
  registration — fire-and-forget framework threads go through
  ``ray_tpu._private.sanitizer.spawn``).
* RT302 — ObjectRef obtained but neither gotten, awaited, passed on nor
  stored; deliberate fire-and-forget is spelled ``# ray-tpu: detached``.
* RT303 — KV key written under a dynamic prefix with no matching
  delete/GC anywhere in the same subsystem directory.
* RT304 — the ``except`` path skips a release the happy path performs
  (the exact shape of the "dead worker leaks one pinned blob" class).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import dataflow
from .lint import Finding, ModuleContext, Rule, register, walk_same_scope

#: Marker that makes a fire-and-forget ObjectRef explicit (RT302).
DETACHED_MARKER = "ray-tpu: detached"

_FAMILY_HINT = {
    "pin": "unpin it on every path (finally/except included)",
    "lock": "release() on every path — or use `with`",
    "file": "close() on every path — or use `with open(...)`",
    "thread": "join() it, store it, or spawn it through "
              "ray_tpu._private.sanitizer.spawn (tracked registry)",
}


def _function_leaks(ctx: ModuleContext):
    """One dataflow pass per module, shared by RT301/RT304."""
    cached = getattr(ctx, "_rt3_leaks", None)
    if cached is None:
        cached = []
        for fn in ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for leak in dataflow.analyze_function(fn):
                cached.append((fn, leak))
        ctx._rt3_leaks = cached
    return cached


@register
class ResourceNotReleased(Rule):
    id = "RT301"
    scope = "internal"
    dataflow = True
    summary = "resource acquired but not released on all paths"
    rationale = ("An acquire (pin / lock.acquire / open / Thread.start) "
                 "with a path to function exit that never releases it "
                 "leaks one resource per call — invisible per-node, "
                 "fatal to long-run goodput.")
    example_bad = (
        "def stage(store, oid, flag):\n"
        "    store.try_pin(oid)\n"
        "    if flag:\n"
        "        return None      # leaks the pin\n"
        "    store.try_unpin(oid)\n")
    example_good = (
        "def stage(store, oid, flag):\n"
        "    store.try_pin(oid)\n"
        "    try:\n"
        "        if flag:\n"
        "            return None\n"
        "    finally:\n"
        "        store.try_unpin(oid)\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn, leak in _function_leaks(ctx):
            if leak.kind != "all-paths":
                continue
            res = leak.resource
            yield ctx.finding(
                self, res.call,
                f"{res.label} in {fn.name}(): acquired but not released "
                f"on every path — {_FAMILY_HINT[res.family]}")


@register
class ExceptPathSkipsRelease(Rule):
    id = "RT304"
    scope = "internal"
    dataflow = True
    summary = "except path skips the release the happy path performs"
    rationale = ("The happy path releases (or hands off) the resource; "
                 "an except handler between acquire and release that "
                 "returns/raises without releasing leaks exactly when "
                 "something already went wrong — the least-tested path.")
    example_bad = (
        "ref = put(blob)\n"
        "_control(\"pin_object\", ref.binary())\n"
        "try:\n"
        "    kv_put(key, ref)\n"
        "except Exception:\n"
        "    return           # pin leaks when the KV write fails\n"
        "self._pinned = ref\n")
    example_good = (
        "ref = put(blob)\n"
        "_control(\"pin_object\", ref.binary())\n"
        "try:\n"
        "    kv_put(key, ref)\n"
        "except Exception:\n"
        "    _control(\"unpin_object\", ref.binary())\n"
        "    return\n"
        "self._pinned = ref\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn, leak in _function_leaks(ctx):
            if leak.kind != "except-path":
                continue
            res = leak.resource
            handler = f" (handler at line {leak.handler_line})" \
                if leak.handler_line else ""
            f = ctx.finding(
                self, res.call,
                f"{res.label} in {fn.name}(): the except path{handler} "
                f"exits without the release the happy path performs — "
                f"release in the handler or a finally")
            # Suppressible at the acquire line or the handler line.
            if leak.handler_line:
                f = Finding(f.rule, f.path, f.line, f.col, f.message,
                            f.anchor_lines + (leak.handler_line,))
            yield f


@register
class DanglingObjectRef(Rule):
    id = "RT302"
    scope = "internal"
    dataflow = True
    summary = "ObjectRef obtained but never consumed, stored or marked " \
              "detached"
    rationale = ("A `.remote()` result that is neither gotten, awaited, "
                 "passed on nor stored pins its task's output in the "
                 "object store until job end and silently swallows the "
                 "task's errors; deliberate fire-and-forget must say so "
                 "with `# ray-tpu: detached`.")
    example_bad = "h.refresh.remote()   # result and errors dropped\n"
    example_good = ("h.refresh.remote()  # ray-tpu: detached — "
                    "best-effort cache warm\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        scopes: List[ast.AST] = [ctx.tree]
        scopes += ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef)
        for scope in scopes:
            yield from self._check_scope(ctx, scope)

    def _check_scope(self, ctx: ModuleContext,
                     scope: ast.AST) -> Iterator[Finding]:
        for stmt in walk_same_scope(scope):
            if isinstance(stmt, ast.Expr) and \
                    self._is_remote_call(stmt.value):
                if self._detached(ctx, stmt.lineno):
                    continue
                yield ctx.finding(
                    self, stmt,
                    "`.remote()` result discarded: get/await/store the "
                    "ref, or mark deliberate fire-and-forget with "
                    "`# ray-tpu: detached`")
            elif isinstance(stmt, ast.Assign) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    stmt.targets[0].id != "_" and \
                    self._is_remote_call(stmt.value):
                name = stmt.targets[0].id
                if self._detached(ctx, stmt.lineno):
                    continue
                if not self._used_later(scope, stmt, name):
                    yield ctx.finding(
                        self, stmt,
                        f"ObjectRef bound to `{name}` is never used: "
                        f"get/await/store it, or mark the line "
                        f"`# ray-tpu: detached`")

    @staticmethod
    def _is_remote_call(node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "remote"

    @staticmethod
    def _detached(ctx: ModuleContext, lineno: int) -> bool:
        if 1 <= lineno <= len(ctx.lines):
            return DETACHED_MARKER in ctx.lines[lineno - 1]
        return False

    @staticmethod
    def _used_later(scope: ast.AST, assign: ast.Assign, name: str) -> bool:
        # Loads of the name AFTER the binding (a Load before it consumed
        # a previous binding's ref, so a rebinding whose result is never
        # read must still be flagged).  Inside a loop execution order is
        # circular — a textually earlier Load runs after the rebinding
        # on the next iteration — so any Load in the scope counts then.
        # Nested defs are included either way: closures legitimately
        # consume the ref later.
        in_loop = any(
            n.lineno <= assign.lineno <= getattr(n, "end_lineno",
                                                 n.lineno)
            for n in ast.walk(scope)
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While)))
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Load) and \
                    (in_loop or node.lineno > assign.lineno):
                return True
        return False


# -- RT303: KV prefix hygiene ----------------------------------------------


def _kv_call_kind(call: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """("put"|"del", key_expr) for any of the KV write/delete shapes:
    ``kv_put(...)`` / ``ctl_kv_put(...)`` / ``_kv_put(...)`` helpers and
    ``_control("kv_put", key, ...)``."""
    seg = None
    if isinstance(call.func, ast.Attribute):
        seg = call.func.attr
    elif isinstance(call.func, ast.Name):
        seg = call.func.id
    if seg is None:
        return None
    if seg == "_control" and call.args and \
            isinstance(call.args[0], ast.Constant):
        verb = call.args[0].value
        if verb in ("kv_put", "kv_del") and len(call.args) > 1:
            return ("put" if verb == "kv_put" else "del", call.args[1])
        return None
    if seg.endswith("kv_put") and call.args:
        return ("put", call.args[0])
    if seg.endswith("kv_del") or seg.endswith("kv_delete_prefix"):
        if call.args:
            return ("del", call.args[0])
    return None


def _module_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _key_prefix(expr: ast.AST,
                consts: Dict[str, str]) -> Tuple[Optional[str], bool]:
    """(leading literal prefix, fully_literal).  ``(None, False)`` =
    statically unresolvable (variable/call-built key)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value, True
    if isinstance(expr, ast.Name):
        v = consts.get(expr.id)
        return (v, True) if v is not None else (None, False)
    if isinstance(expr, ast.JoinedStr):
        prefix = ""
        for part in expr.values:
            if isinstance(part, ast.Constant) and \
                    isinstance(part.value, str):
                prefix += part.value
            else:
                return (prefix or None), False
        return prefix, True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left, lf = _key_prefix(expr.left, consts)
        if left is None:
            return None, False
        if not lf:
            return left, False
        right, rf = _key_prefix(expr.right, consts)
        return left + (right or ""), lf and rf and right is not None
    return None, False


def _collect_kv(tree: ast.Module, consts: Dict[str, str]):
    """(puts, del_prefixes, del_wildcard) for one module."""
    puts: List[Tuple[ast.Call, str]] = []
    dels: Set[str] = set()
    wildcard = False
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _kv_call_kind(node)
        if kind is None:
            continue
        which, key = kind
        prefix, fully = _key_prefix(key, consts)
        if which == "put":
            # Fully-literal keys are bounded singletons (a verdict slot,
            # a registry blob) — only dynamic keys can accumulate.
            if prefix and not fully:
                puts.append((node, prefix))
        else:
            if prefix:
                dels.add(prefix)
            else:
                wildcard = True  # generic GC loop (key from kv_keys())
    return puts, dels, wildcard


_subsystem_cache: Dict[str, Tuple[Set[str], bool]] = {}


def _subsystem_dels(dirpath: str) -> Tuple[Set[str], bool]:
    """Delete prefixes declared anywhere in the module's directory (the
    subsystem: ray_tpu/train, ray_tpu/serve, ...).  Cached per dir."""
    cached = _subsystem_cache.get(dirpath)
    if cached is not None:
        return cached
    dels: Set[str] = set()
    wildcard = False
    try:
        fnames = sorted(os.listdir(dirpath))
    except OSError:
        fnames = []
    for fname in fnames:
        if not fname.endswith(".py"):
            continue
        try:
            with open(os.path.join(dirpath, fname),
                      encoding="utf-8", errors="replace") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        _, file_dels, file_wild = _collect_kv(tree, _module_consts(tree))
        dels |= file_dels
        wildcard = wildcard or file_wild
    _subsystem_cache[dirpath] = (dels, wildcard)
    return dels, wildcard


@register
class KvPrefixNeverDeleted(Rule):
    id = "RT303"
    scope = "internal"
    dataflow = True
    summary = "KV key written under a prefix with no delete/GC in the " \
              "same subsystem"
    rationale = ("A per-run/per-rank KV key (dynamic suffix) written "
                 "with no kv_del under a matching prefix anywhere in "
                 "its subsystem grows the head's KV store forever — "
                 "every run leaks its keys into the next.")
    example_bad = ("_control(\"kv_put\", f\"myfeat/{run_id}/x\", blob)\n"
                   "# ... no kv_del under myfeat/ anywhere\n")
    example_good = ("_control(\"kv_put\", f\"myfeat/{run_id}/x\", blob)\n"
                    "# consumer, after processing:\n"
                    "_control(\"kv_del\", key)  # generic GC of read "
                    "keys\n")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "kv_put" not in ctx.source:
            return
        consts = _module_consts(ctx.tree)
        puts, local_dels, local_wild = _collect_kv(ctx.tree, consts)
        if not puts:
            return
        dirpath = os.path.dirname(os.path.abspath(ctx.path)) \
            if os.path.exists(ctx.path) else None
        if dirpath is not None:
            sub_dels, sub_wild = _subsystem_dels(dirpath)
        else:  # snippet: only the module itself is visible
            sub_dels, sub_wild = local_dels, local_wild
        for call, prefix in puts:
            if sub_wild or any(prefix.startswith(d) or d.startswith(prefix)
                               for d in sub_dels):
                continue
            yield ctx.finding(
                self, call,
                f"KV keys under {prefix!r} are written but never "
                f"deleted in this subsystem: add a kv_del/GC for the "
                f"prefix (consumed keys, end-of-run sweep), or the head "
                f"KV grows per run")
