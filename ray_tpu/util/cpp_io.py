"""Python side of the C++ tensor hand-off (cpp/include/ray_tpu/
tensor_writer.hpp).

A native producer (data loader, feature pipeline) writes tensors into a
POSIX shm segment with a small typed header; ``import_tensors`` maps
them as ZERO-COPY numpy views ready for ``jax.device_put`` — the
native-IO feed path (reference analog: the C++ user API's object
hand-off through plasma).  ``export_tensors`` writes the same layout for
C++ consumers (the inverse of cpp/include/ray_tpu/object_reader.hpp,
which reads store payload framing directly).

Layout (little endian): u32 magic "RTPT", u32 n_tensors, then per tensor
{u32 dtype_code, u32 ndim, u64 shape[ndim], u64 nbytes, u64 abs_offset}
with tensor bytes 64-byte aligned at their offsets.
"""

from __future__ import annotations

import struct
from multiprocessing import shared_memory
from typing import List, Tuple

import numpy as np

_MAGIC = 0x52545054
_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "int8",
           "uint16", "int16", "uint32", "uint64", "float16", "bfloat16",
           "bool"]


def _np_dtype(code: int):
    name = _DTYPES[code]
    if name == "bfloat16":
        try:
            import ml_dtypes
            return np.dtype(ml_dtypes.bfloat16)
        except ImportError:
            return np.dtype(np.uint16)  # raw bits view
    return np.dtype(name)


def import_tensors(segment_name: str) -> Tuple[List[np.ndarray], object]:
    """Map a C++-written tensor segment; returns (views, keepalive).

    The arrays alias the shared memory (zero copies); hold ``keepalive``
    as long as any view is in use.  Unlink the segment via
    ``keepalive.unlink()`` when the hand-off is consumed."""
    shm = shared_memory.SharedMemory(name=segment_name.lstrip("/"))
    buf = shm.buf
    magic, n = struct.unpack_from("<II", buf, 0)
    if magic != _MAGIC:
        shm.close()
        raise ValueError(
            f"segment {segment_name!r} is not a sealed tensor segment")
    off = 8
    views: List[np.ndarray] = []
    for _ in range(n):
        code, ndim = struct.unpack_from("<II", buf, off)
        off += 8
        shape = struct.unpack_from(f"<{ndim}Q", buf, off)
        off += 8 * ndim
        nbytes, data_off = struct.unpack_from("<QQ", buf, off)
        off += 16
        dt = _np_dtype(code)
        arr = np.frombuffer(buf, dtype=dt,
                            count=nbytes // dt.itemsize,
                            offset=data_off).reshape(shape)
        views.append(arr)
    return views, shm


def export_tensors(segment_name: str, arrays: List[np.ndarray]) -> str:
    """Write arrays into a tensor segment a C++ consumer can map."""
    header = 8
    for a in arrays:
        header += 8 + 8 * a.ndim + 16
    offsets = []
    off = header
    for a in arrays:
        off = (off + 63) & ~63
        offsets.append(off)
        off += a.nbytes
    shm = shared_memory.SharedMemory(name=segment_name.lstrip("/"),
                                     create=True, size=max(off, 1))
    buf = shm.buf
    dst = None
    try:
        pos = 8
        for a, data_off in zip(arrays, offsets):
            code = _DTYPES.index(_dtype_name(a.dtype))
            struct.pack_into("<II", buf, pos, code, a.ndim)
            pos += 8
            struct.pack_into(f"<{a.ndim}Q", buf, pos, *a.shape)
            pos += 8 * a.ndim
            struct.pack_into("<QQ", buf, pos, a.nbytes, data_off)
            pos += 16
            dst = np.frombuffer(buf, dtype=np.uint8, count=a.nbytes,
                                offset=data_off)
            np.copyto(dst, np.ascontiguousarray(a).view(np.uint8).ravel())
        # Magic last: a valid header means "sealed".
        struct.pack_into("<II", buf, 0, _MAGIC, len(arrays))
    finally:
        # Every view into shm.buf must die before close() (BufferError
        # on exported pointers otherwise).
        del dst, buf
        shm.close()
    return segment_name


def _dtype_name(dt: np.dtype) -> str:
    name = dt.name
    if name == "bfloat16":
        return "bfloat16"
    if name not in _DTYPES:
        raise TypeError(f"unsupported dtype for C++ hand-off: {dt}")
    return name
