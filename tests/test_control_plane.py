"""Control-plane telescope: scheduler decision ring, explain(), the
lifecycle stage attribution, the `ray-tpu sched` / `ray-tpu task why`
CLIs, and the tier-1 smoke of ``bench.py --spec control_plane --fast``.

The offline harness half runs a REAL ClusterScheduler against fake
NodeInfos (no workers), so every reason code — pending_deps, infeasible,
draining, bundle_unavailable — is asserted end to end without a cluster;
the live half drives the same answers through the job-server REST
surface and the click CLIs.
"""

from __future__ import annotations

import json
import os
import sys
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _wait_for(predicate, timeout_s: float = 10.0, interval_s: float = 0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval_s)
    raise AssertionError("condition not met within timeout")


@pytest.fixture()
def harness():
    import bench
    made = []

    def make(num_nodes, cpus_per_node=4.0):
        h = bench._SchedHarness(num_nodes, cpus_per_node=cpus_per_node)
        made.append(h)
        return h

    yield make
    for h in made:
        h.close()


class TestDecisionRingAndExplain:
    def test_placed_task_records_decision(self, harness):
        h = harness(3)
        placed = []
        h.sched.submit(h.make_spec(1), lambda s, n: placed.append(n))
        _wait_for(lambda: placed)
        rec = h.sched.ring.latest_for(h.make_spec(1).task_id.hex())
        assert rec is not None
        assert rec["kind"] in ("inline", "loop")
        assert rec["node_id"] == placed[0].hex()
        assert rec["attempt"] == 1
        assert rec["candidates"] >= 1
        assert "CPU:1" in rec["sched_class"]

    def test_pending_deps_explains_unresolved_objects(self, harness):
        h = harness(2)
        dep = h.make_object_id(7)
        h.pending_objects.add(dep)
        spec = h.make_spec(1, deps=(dep,))
        h.sched.submit(spec, lambda s, n: None)
        out = h.sched.explain_task(spec.task_id)
        assert out["status"] == "pending_deps"
        assert out["reasons"] == ["pending_deps"]
        assert out["unresolved_deps"] == [dep.hex()]

    def test_infeasible_parks_and_explains_with_gap(self, harness):
        h = harness(2)  # 4 CPUs per node, no GPU anywhere
        spec = h.make_spec(1, resources={"CPU": 1.0, "GPU": 2.0})
        h.sched.submit(spec, lambda s, n: None)
        # The loop parks the class as infeasible (not rescanned per wake).
        _wait_for(lambda: h.sched.queue_depths()["infeasible"] == 1)
        out = h.sched.explain_task(spec.task_id)
        assert out["status"] == "infeasible"
        assert "infeasible" in out["reasons"]
        assert out["closest_fit"]["gap"] == {"GPU": 2.0}
        # The ring carries the reject + park decisions.
        rec = h.sched.ring.latest_for(spec.task_id.hex())
        assert rec["kind"] in ("reject", "infeasible")
        assert rec["rejected"].get("infeasible")

    def test_infeasible_revived_by_add_node(self, harness):
        from ray_tpu._private.controller import NodeInfo
        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.resources import ResourceSet
        h = harness(1)
        spec = h.make_spec(1, resources={"GPU": 1.0})
        placed = []
        h.sched.submit(spec, lambda s, n: placed.append(n))
        _wait_for(lambda: h.sched.queue_depths()["infeasible"] == 1)
        h.sched.add_node(NodeInfo(
            NodeID(b"\x99" * NodeID.SIZE), "gpu-node",
            ResourceSet({"CPU": 4.0, "GPU": 2.0})))
        _wait_for(lambda: placed)
        assert placed[0].hex() == (b"\x99" * NodeID.SIZE).hex()

    def test_draining_rejection_reason(self, harness):
        from ray_tpu._private.scheduler import \
            NodeAffinitySchedulingStrategy
        h = harness(1)
        h.sched.set_draining(h.node_ids[0], True)
        # Hard affinity to the draining node: queued with the drain
        # fence named as the reason.
        spec = h.make_spec(1)
        spec.scheduling_strategy = NodeAffinitySchedulingStrategy(
            h.node_ids[0], soft=False)
        h.sched.submit(spec, lambda s, n: None)
        out = h.sched.explain_task(spec.task_id)
        assert "draining" in out["reasons"]
        assert "affinity_miss" in out["reasons"]
        # A plain task on a fully-draining cluster also names the fence.
        plain = h.make_spec(2)
        h.sched.submit(plain, lambda s, n: None)
        out = h.sched.explain_task(plain.task_id)
        assert out["rejected"].get("draining") == 1
        assert "draining" in out["reasons"]

    def test_pg_bundle_miss_reason(self, harness):
        from ray_tpu._private.controller import (BundleInfo,
                                                 PlacementGroupInfo)
        from ray_tpu._private.ids import PlacementGroupID
        from ray_tpu._private.resources import ResourceSet
        h = harness(2)  # 4 CPUs/node: a 64-CPU bundle can never commit
        pg = PlacementGroupInfo(
            PlacementGroupID(b"\x02" * PlacementGroupID.SIZE), "test_pg",
            "PACK", [BundleInfo(0, ResourceSet({"CPU": 64.0}))])
        assert h.sched.create_placement_group(pg) is False
        spec = h.make_spec(1, pg=pg.pg_id, bundle_index=0)
        h.sched.submit(spec, lambda s, n: None)
        out = h.sched.explain_task(spec.task_id)
        assert out["reasons"] == ["bundle_unavailable"]
        assert out["pg"]["committed_bundles"] == []
        # The PG's own failed prepare is on the ring too.
        rec = h.sched.ring.latest_for(pg.pg_id.hex())
        assert rec["kind"] == "pg_reject"
        assert rec["rejected"].get("bundle_unavailable") == 1

    def test_pg_commit_decision_recorded(self, harness):
        from ray_tpu._private.controller import (BundleInfo,
                                                 PlacementGroupInfo)
        from ray_tpu._private.ids import PlacementGroupID
        from ray_tpu._private.resources import ResourceSet
        h = harness(2)
        pg = PlacementGroupInfo(
            PlacementGroupID(b"\x03" * PlacementGroupID.SIZE), "ok_pg",
            "PACK", [BundleInfo(0, ResourceSet({"CPU": 2.0}))])
        assert h.sched.create_placement_group(pg) is True
        rec = h.sched.ring.latest_for(pg.pg_id.hex())
        assert rec["kind"] == "pg_commit"
        assert rec["node_id"]

    def test_ring_bounded_and_counts_drops(self):
        from ray_tpu.schedview import DecisionRing
        ring = DecisionRing(capacity=64)
        for i in range(300):
            ring.push("loop", f"{i:04x}", "t", None, 1, None, "n", 1)
        stats = ring.stats()
        assert stats["size"] == 64
        assert stats["num_dropped"] == 300 - 64
        assert stats["counts"]["loop"] == 300
        assert len(ring.snapshot(limit=1000)) == 64

    def test_ring_disabled_records_nothing(self, harness):
        from ray_tpu import schedview
        h = harness(2)
        schedview.set_enabled(False)
        try:
            placed = []
            h.sched.submit(h.make_spec(1), lambda s, n: placed.append(n))
            _wait_for(lambda: placed)
            assert h.sched.ring.stats()["total"] == 0
        finally:
            schedview.set_enabled(True)


class TestEventBufferStats:
    def test_dropped_and_backlog_visible(self):
        from ray_tpu._private.events import (FINISHED, RUNNING,
                                             TaskEventBuffer)
        buf = TaskEventBuffer(max_events=4)
        for i in range(10):
            buf.record(f"{i:02x}", RUNNING)
        buf._fold()
        stats = buf.stats()
        assert stats["num_events"] == 4
        assert stats["num_dropped"] == 6
        assert stats["fold_backlog"] == 0
        buf.record("ff", FINISHED)
        assert buf.stats()["fold_backlog"] == 1

    def test_monotonic_stage_waits(self):
        from ray_tpu._private.events import (FINISHED, PLACED, READY,
                                             RUNNING, SUBMITTED_TO_NODE,
                                             PENDING_ARGS,
                                             TaskEventBuffer)
        buf = TaskEventBuffer()
        buf.record("aa", PENDING_ARGS, name="t")
        time.sleep(0.02)
        buf.record("aa", READY)
        buf.record("aa", PLACED)
        buf.record("aa", SUBMITTED_TO_NODE)
        buf.record("aa", RUNNING)
        time.sleep(0.01)
        buf.record("aa", FINISHED)
        rec = buf.snapshot({"task_id": "aa"}, 1)[0]
        waits = rec["stage_waits"]
        assert waits["deps"] >= 0.015
        assert waits["run"] >= 0.005
        assert set(waits) == {"deps", "queue", "dispatch", "startup",
                              "run"}

    def test_filter_pushdown_and_limit(self):
        from ray_tpu._private.events import (FINISHED, RUNNING,
                                             TaskEventBuffer)
        buf = TaskEventBuffer()
        for i in range(50):
            buf.record(f"{i:02x}", RUNNING, name=f"fn{i % 2}")
        for i in range(10):
            buf.record(f"{i:02x}", FINISHED)
        out = buf.snapshot({"state": FINISHED}, limit=4)
        assert len(out) == 4
        assert all(e["state"] == FINISHED for e in out)
        # Summary with state filter + scan limit.
        summ = buf.summary(states=[FINISHED])
        assert sum(sum(v.values()) for v in summ.values()) == 10
        assert buf.summary(limit=5)
        # Stage-latency filter: only tasks that entered "run".
        out = buf.snapshot(stage="run", min_stage_wait_s=0.0, limit=100)
        assert len(out) == 10

    def test_find_ids_prefix(self):
        from ray_tpu._private.events import RUNNING, TaskEventBuffer
        buf = TaskEventBuffer()
        buf.record("abcd01", RUNNING)
        buf.record("abcd02", RUNNING)
        buf.record("ef99", RUNNING)
        assert set(buf.find_ids("abcd")) == {"abcd01", "abcd02"}
        assert buf.find_ids("zz") == []


class TestLiveExplainAndCLI:
    """End-to-end through a real runtime, the job-server REST surface
    and the click CLIs (`ray-tpu task why`, `ray-tpu sched`)."""

    @pytest.fixture()
    def server(self, ray_start_isolated):
        from ray_tpu.job_submission.manager import JobManager
        from ray_tpu.job_submission.server import JobServer
        server = JobServer(JobManager(), port=0)
        yield server
        server.stop()

    def _cli(self, args):
        from click.testing import CliRunner

        from ray_tpu.scripts.cli import cli
        return CliRunner().invoke(cli, args)

    def test_task_why_pending_deps_and_infeasible(self, server):
        import ray_tpu

        @ray_tpu.remote
        def _sleepy():
            time.sleep(6)
            return 1

        @ray_tpu.remote
        def _add(x, y=0):
            return x

        dep = _sleepy.remote()
        child = _add.remote(dep)
        gpu = _add.options(resources={"GPU": 1.0}).remote(1)
        time.sleep(0.4)
        addr = server.address

        child_tid = child._id.task_id().hex()
        r = self._cli(["task", "why", "--address", addr, child_tid])
        assert r.exit_code == 0, r.output
        assert "pending_deps" in r.output
        assert "waiting on object" in r.output

        # Prefix lookup: the first 12 chars resolve to the same task.
        gpu_tid = gpu._id.task_id().hex()
        r = self._cli(["task", "why", "--address", addr, gpu_tid])
        assert r.exit_code == 0, r.output
        assert "infeasible" in r.output
        assert "GPU" in r.output  # the named resource gap

        # Finished task: explains why it landed where it landed.
        done = _add.remote(1)
        ray_tpu.get(done)
        time.sleep(0.1)
        r = self._cli(["task", "why", "--address", addr,
                       done._id.task_id().hex()])
        assert r.exit_code == 0, r.output
        assert "status: finished" in r.output
        assert "last decision" in r.output

        # Unknown id exits non-zero with a readable message.
        r = self._cli(["task", "why", "--address", addr, "feedface"])
        assert r.exit_code == 1
        assert "no task" in r.output
        ray_tpu.get(dep)
        ray_tpu.get(child)

    def test_sched_cli_shows_rates_queues_and_buffer(self, server):
        import ray_tpu

        @ray_tpu.remote
        def _one():
            return 1

        ray_tpu.get([_one.remote() for _ in range(10)])
        r = self._cli(["sched", "--address", server.address, "-n", "5"])
        assert r.exit_code == 0, r.output
        assert "decisions/s" in r.output
        assert "queues:" in r.output
        assert "ready:" in r.output
        assert "task events:" in r.output
        assert "fold backlog" in r.output
        # -n 5 prints decision records.
        assert "[" in r.output and "cands=" in r.output

    def test_state_api_and_rest_surface(self, server):
        import urllib.request

        import ray_tpu
        from ray_tpu.util import state as rstate

        @ray_tpu.remote
        def _one():
            return 1

        ray_tpu.get(_one.remote())
        stats = rstate.sched_stats()
        assert stats["decisions"]["total"] >= 1
        assert "ready" in stats["queues"]
        assert rstate.sched_decisions(limit=5)

        with urllib.request.urlopen(
                server.address + "/api/cluster/sched?decisions=3") as resp:
            out = json.loads(resp.read())
        assert out["stats"]["decisions"]["total"] >= 1
        assert isinstance(out.get("decisions"), list)

    def test_debug_bundle_carries_sched_decisions(self, ray_start_isolated):
        import ray_tpu
        from ray_tpu.util import state as rstate

        @ray_tpu.remote
        def _one():
            return 1

        ray_tpu.get(_one.remote())
        path = rstate.debug_dump(reason="sched_test")
        fname = os.path.join(path, "sched_decisions.json")
        assert os.path.exists(fname)
        with open(fname) as f:
            doc = json.load(f)
        assert doc["stats"]["total"] >= 1
        assert "queues" in doc
        assert isinstance(doc["decisions"], list)


class TestControlPlaneBenchGate:
    """The checked-in BENCH_control_plane.json is the scheduler-scale
    baseline the next control-plane perf PR measures against."""

    def _load(self):
        path = os.path.join(REPO_ROOT, "BENCH_control_plane.json")
        assert os.path.exists(path), \
            "BENCH_control_plane.json baseline missing"
        with open(path) as f:
            return path, json.load(f)

    def test_checked_in_baseline_holds_sla(self):
        _path, doc = self._load()
        assert doc["sla"]["pass"] is True
        assert doc["sla"]["at_least_1k_nodes"]
        assert doc["sla"]["every_pending_explained"]
        assert doc["sla"]["overhead_within_budget"]
        assert doc["overhead"]["overhead_pct"] < 2.0
        assert doc["sla"]["scheduler_lock_profiled"]
        assert doc["sla"]["lock_profile_within_budget"]
        assert doc["lock_profile_overhead"]["overhead_pct"] < 2.0
        cont = doc["contention"]
        assert cont["hottest_scheduler_site"], cont
        hot = cont["scheduler_sites"][0]
        assert hot["acquires"] > 0 and hot["wait_total_s"] >= 0.0
        assert "1000" in doc["scales"]
        s1k = doc["scales"]["1000"]
        assert s1k["decisions_per_s"] > 0
        assert s1k["decision_p99_us"] > s1k["decision_p50_us"] > 0
        sat = doc["saturation"]
        assert sat["explain_empty"] == 0
        for reason in ("insufficient_resources", "pending_deps",
                       "infeasible", "bundle_unavailable", "draining"):
            assert sat["explain_reasons"].get(reason, 0) > 0, reason

    def test_compare_gate_covers_control_plane_metrics(self):
        import bench
        path, doc = self._load()
        out = bench.compare_bench(path, path, threshold=0.10)
        assert not out["regressions"]
        flat = bench._flatten_bench(doc)
        gated = [p for p in flat
                 if bench._metric_direction(p) is not None]
        assert any("decisions_per_s" in p for p in gated)
        assert any("decision_p99_us" in p for p in gated)
        assert any("overhead_pct" in p for p in gated)
        assert any(p.endswith("sla.pass") for p in gated)


class TestControlPlaneBenchSmoke:
    def test_fast_bench_end_to_end(self, tmp_path):
        """`bench.py --spec control_plane --fast` wired into tier-1 as
        a smoke, in a subprocess with a hard wall bound: decision
        scale at 100+1000 fake nodes, the saturation phase where every
        pending task explains itself, the e2e core, and the tracing-
        overhead gate."""
        import subprocess

        out = str(tmp_path / "BENCH_control_plane.json")
        code = (
            "import bench, json\n"
            "try:\n"
            f"    bench.bench_control_plane(fast=True, out_path={out!r})\n"
            "except SystemExit:\n"
            "    pass\n"
            "print('BENCH_DONE')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="", XLA_FLAGS="")

        def run_once():
            proc = subprocess.run(
                [sys.executable, "-u", "-c", code], cwd=REPO_ROOT,
                env=env, capture_output=True, text=True, timeout=420)
            assert proc.returncode == 0 and "BENCH_DONE" in proc.stdout, \
                f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n" \
                f"{proc.stderr[-4000:]}"
            with open(out) as f:
                return json.load(f)

        doc = run_once()
        sla = doc["sla"]
        noisy = ("overhead_within_budget", "lock_profile_within_budget")
        if not sla["pass"] and all(
                v for k, v in sla.items()
                if isinstance(v, bool)
                and k != "pass" and k not in noisy):
            # The two overhead gates are the criteria with residual
            # measurement noise on a one-core CI box (true costs well
            # under the 2% budgets, but block-to-block floors swing a
            # few percent); everything else is deterministic.  One
            # retry bounds the flake rate without weakening the strict
            # gate on the checked-in FULL baseline above.
            doc = run_once()
        assert doc["sla"]["pass"] is True, doc["sla"]
        assert doc["saturation"]["explain_empty"] == 0
        assert doc["scales"]["1000"]["decisions_per_s"] > 0
