"""Small MLP model for train-loop tests (the reference's test workloads use
toy torch models similarly, reference: python/ray/train/examples)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 32
    hidden: int = 64
    out_dim: int = 10
    layers: int = 2


def init_mlp(cfg: MLPConfig, key: jax.Array) -> Dict[str, Any]:
    params = {}
    dims = [cfg.in_dim] + [cfg.hidden] * (cfg.layers - 1) + [cfg.out_dim]
    keys = jax.random.split(key, len(dims) - 1)
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"w{i}"] = jax.random.normal(keys[i], (a, b)) * (a ** -0.5)
        params[f"b{i}"] = jnp.zeros((b,))
    return params


def mlp_forward(params: Dict[str, Any], x: jax.Array) -> jax.Array:
    n = len(params) // 2
    for i in range(n):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params: Dict[str, Any], batch: Dict[str, jax.Array]) -> jax.Array:
    logits = mlp_forward(params, batch["x"])
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(
        jnp.take_along_axis(logp, batch["y"][:, None], axis=-1))
