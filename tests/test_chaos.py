"""Chaos: asio-style delay injection sweep (reference:
RAY_testing_asio_delay_us, src/ray/common/ray_config_def.h:918 — the
practical race-shaker; every send sleeps a random 0..delay_us)."""

import pytest

import ray_tpu
from ray_tpu._private.config import Config


@pytest.fixture
def delayed_runtime():
    # Delay must be set BEFORE init so the NodeManager picks it up.
    Config.initialize()
    Config.set("testing_delay_us", 3000)  # up to 3ms on every send
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    Config.set("testing_delay_us", 0)
    ray_tpu.shutdown()


class TestDelayChaos:
    def test_workload_correct_under_message_delays(self, delayed_runtime):
        """Tasks, dependency chains, actor ordering and puts all stay
        correct when every control message is randomly delayed — the
        orderings the runtime relies on must come from the protocol, not
        from timing luck."""

        @ray_tpu.remote
        def add(a, b):
            return a + b

        # Dependency diamond fan-in under delays.
        leaves = [add.remote(i, i) for i in range(8)]
        mids = [add.remote(leaves[i], leaves[i + 1]) for i in range(0, 8, 2)]
        total = ray_tpu.get(add.remote(
            add.remote(mids[0], mids[1]), add.remote(mids[2], mids[3])))
        assert total == sum(2 * i for i in range(8))

        # Actor method ordering survives delayed sends.
        @ray_tpu.remote
        class Seq:
            def __init__(self):
                self.log = []

            def push(self, i):
                self.log.append(i)
                return i

            def all(self):
                return self.log

        s = Seq.remote()
        refs = [s.push.remote(i) for i in range(20)]
        ray_tpu.get(refs)
        assert ray_tpu.get(s.all.remote()) == list(range(20))

        # Puts + large args round-trip.
        import numpy as np
        big = ray_tpu.put(np.arange(200_000))
        assert ray_tpu.get(add.remote(big, 1))[-1] == 200_000

    def test_retry_under_delays(self, delayed_runtime):
        @ray_tpu.remote(max_retries=2)
        def flaky_once():
            import os
            marker = "/tmp/ray_tpu_chaos_marker"
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)
            os.remove(marker)
            return "recovered"

        assert ray_tpu.get(flaky_once.remote(), timeout=60) == "recovered"
