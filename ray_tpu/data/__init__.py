"""ray_tpu.data — streaming datasets feeding TPU meshes (Ray Data
equivalent)."""

from .block import Block, BlockAccessor
from .dataset import (Dataset, GroupedDataset, from_arrow, from_items,
                      from_numpy, from_pandas, range, read_binary_files,
                      read_csv, read_images, read_json, read_parquet,
                      read_tfrecord)
from .iterator import device_put_iterator, iter_batches

__all__ = [
    "Dataset", "GroupedDataset", "Block", "BlockAccessor", "range",
    "from_arrow", "from_items", "from_numpy", "from_pandas",
    "read_parquet", "read_csv",
    "read_binary_files", "read_images", "read_tfrecord",
    "read_json", "iter_batches", "device_put_iterator",
]
