"""Flag/config system for the ray_tpu runtime.

Mirrors the capability of the reference's single-source flag registry
(reference: src/ray/common/ray_config_def.h — 241 ``RAY_CONFIG(type, name,
default)`` macros, each overridable by a ``RAY_<name>`` env var and by a JSON
blob pushed from the frontend at process start).  Here the registry is a
declarative table of typed flags; precedence is

    explicit ``Config.initialize(overrides)``  >  env ``RAY_TPU_<NAME>``  >  default.

Workers inherit the driver's resolved config through a serialized JSON blob in
their spawn environment, so every process in a cluster sees one consistent
view (same contract as RayConfig::initialize in the reference).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

ENV_PREFIX = "RAY_TPU_"
# Env var carrying the driver's resolved config to child worker processes.
CONFIG_BLOB_ENV = "RAY_TPU_CONFIG_BLOB"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: lambda s: int(s, 0),
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    doc: str


class Config:
    """Process-wide typed flag registry with env + JSON-blob overrides."""

    _flags: Dict[str, _Flag] = {}
    _values: Dict[str, Any] = {}
    _lock = threading.Lock()
    _initialized = False

    @classmethod
    def define(cls, name: str, type_: type, default: Any, doc: str = "") -> None:
        cls._flags[name] = _Flag(name, type_, default, doc)

    @classmethod
    def initialize(cls, overrides: Optional[Dict[str, Any]] = None) -> None:
        """Resolve all flags. Called once at init; idempotent refresh allowed."""
        with cls._lock:
            values: Dict[str, Any] = {}
            blob = os.environ.get(CONFIG_BLOB_ENV)
            blob_values = json.loads(blob) if blob else {}
            for name, flag in cls._flags.items():
                val = flag.default
                if name in blob_values:
                    val = blob_values[name]
                env_val = os.environ.get(ENV_PREFIX + name.upper())
                if env_val is not None:
                    val = _PARSERS[flag.type](env_val)
                if overrides and name in overrides:
                    val = overrides[name]
                if val is not None and not isinstance(val, flag.type):
                    val = flag.type(val)
                values[name] = val
            cls._values = values
            cls._initialized = True

    @classmethod
    def get(cls, name: str) -> Any:
        if not cls._initialized:
            cls.initialize()
        try:
            return cls._values[name]
        except KeyError:
            raise KeyError(f"unknown config flag: {name}") from None

    @classmethod
    def set(cls, name: str, value: Any) -> None:
        if not cls._initialized:
            cls.initialize()
        if name not in cls._flags:
            raise KeyError(f"unknown config flag: {name}")
        with cls._lock:
            cls._values[name] = value

    @classmethod
    def blob(cls) -> str:
        """JSON blob of the resolved config, for child process inheritance."""
        if not cls._initialized:
            cls.initialize()
        return json.dumps(cls._values)

    @classmethod
    def all(cls) -> Dict[str, Any]:
        if not cls._initialized:
            cls.initialize()
        return dict(cls._values)


D = Config.define

# --- Object store ----------------------------------------------------------
# Inline threshold mirrors max_direct_call_object_size (reference:
# src/ray/common/ray_config_def.h:245, 100 KiB).
D("max_inline_object_size", int, 100 * 1024,
  "Objects <= this many bytes travel inline in control messages; larger ones "
  "go to the shared-memory store.")
D("object_store_memory", int, 2 * 1024 ** 3,
  "Soft cap on bytes resident in the host shared-memory object store.")
D("object_spill_dir", str, "",
  "Directory for spilling objects when the store exceeds its cap "
  "(empty = <session_dir>/spill).")
D("use_native_store", bool, True,
  "Use the C++ arena object store (ray_tpu/_native/store.cc) when a "
  "toolchain is available; falls back to the Python per-segment store.")

# --- Scheduler -------------------------------------------------------------
D("scheduler_spread_threshold", float, 0.5,
  "Hybrid policy: pack onto nodes under this utilization, then spread "
  "(reference: hybrid_scheduling_policy.cc top_k logic).")
D("lease_timeout_s", float, 30.0, "Worker lease request timeout.")
D("max_pending_lease_requests_per_key", int, 10,
  "Pipelined lease requests per scheduling key.")

# --- Worker pool -----------------------------------------------------------
D("num_workers_soft_limit", int, 0,
  "Max resident idle workers per node (0 = num_cpus).")
D("worker_register_timeout_s", float, 60.0,
  "How long to wait for a spawned worker to call back.")
D("worker_idle_kill_s", float, 300.0,
  "Idle workers beyond the soft limit are reaped after this long.")
D("worker_start_method", str, "spawn",
  "multiprocessing start method for worker processes.")

# --- Health / fault tolerance ---------------------------------------------
D("health_check_period_s", float, 1.0,
  "Controller -> node liveness probe period (reference: "
  "gcs_health_check_manager.h timeouts).")
D("health_check_failure_threshold", int, 5,
  "Consecutive missed probes before a node is declared dead.")
D("node_reconnect_grace_s", float, 5.0,
  "After a node's control connection drops, how long the head waits for "
  "it to re-attach (same identity, tasks/actors kept) before running the "
  "node-death fan-out (reference: raylet reconnect after GCS failover).")
D("task_max_retries_default", int, 3, "Default retries for idempotent tasks.")
D("actor_max_restarts_default", int, 0, "Default actor restarts.")
D("enable_object_gc", bool, True,
  "Reference-count driver ObjectRefs and free unreachable objects "
  "(reference: reference_counter.h:44 local-ref tracking).")
D("lineage_max_entries", int, 50000,
  "Bounded lineage table: task specs kept for object reconstruction, "
  "LRU-evicted (reference: ray_config_def.h max_lineage_bytes analog).")
D("head_wal_fsync", bool, False,
  "fsync each head-state WAL append.  Off by default: flush-per-append "
  "already survives head-process death (the protected failure mode); "
  "fsync buys machine-crash durability at write-latency cost.")
D("object_reconstruction_max_attempts", int, 3,
  "How many times a lost object may be reconstructed by re-executing its "
  "producing task (reference: task_manager.h ResubmitTask retry caps).")

# --- Chaos / testing (reference: src/ray/rpc/rpc_chaos.cc:33,
# RAY_testing_rpc_failure) --------------------------------------------------
D("testing_rpc_failure", str, "",
  "Comma list 'method=prob' — injected message-drop probability per RPC "
  "method, for chaos tests.")
D("testing_delay_us", int, 0,
  "Injected artificial delay (microseconds) in message dispatch, for "
  "determinism-shaking tests.")

# --- TPU / accelerator -----------------------------------------------------
D("tpu_chips_per_host_override", int, 0,
  "Force chips-per-host for tests (0 = autodetect).")
D("visible_accelerator_env", str, "TPU_VISIBLE_CHIPS",
  "Env var used to pin a worker to its granted chips (reference: "
  "python/ray/_private/accelerators/tpu.py NOSET/VISIBLE chips plumbing).")

# --- Observability ---------------------------------------------------------
D("task_events_max_num_task_in_gcs", int, 10000,
  "Bounded task-event history size (reference: ray_config_def.h "
  "task_events_max_num_task_in_gcs).")
D("sched_decision_ring_size", int, 4096,
  "Bounded scheduler decision-ring capacity (ray_tpu.schedview): how many "
  "placement decisions `ray-tpu task why` / sched_decisions.json can look "
  "back on.  Tracing itself is toggled by RAY_TPU_SCHED_TRACE.")
D("stack_dump_timeout_s", float, 5.0,
  "How long a cluster-wide stack capture (`ray-tpu stack`, "
  "state.list_stacks) waits for worker replies; non-responders are "
  "reported as unresponsive — itself a diagnostic signal.")
D("debug_bundle_on_worker_death", bool, True,
  "Write a flight-recorder bundle under <session>/debug/ when a worker "
  "dies while running tasks (rate-limited; see "
  "debug_bundle_min_interval_s).")
D("debug_bundle_min_interval_s", float, 60.0,
  "Minimum seconds between automatic worker-death debug bundles, so a "
  "crash loop cannot fill the disk with forensics.")
D("metricsview_interval_s", float, 1.0,
  "Metrics time-series store downsample interval: at most one stored "
  "point per series per interval regardless of flush rate "
  "(ray_tpu.metricsview; retention = interval * metricsview_max_points).")
D("metricsview_max_points", int, 600,
  "Ring capacity per (series, tag-set) in the metrics time-series store "
  "(default 600 points x 1 s interval = 10 min of queryable history).")
D("metricsview_max_series", int, 2048,
  "Hard cap on distinct (series, tag-set) rings the head will track; "
  "overflow increments ray_tpu_metricsview_dropped_total instead of "
  "growing without bound.")
D("debug_bundle_profile_s", float, 0.0,
  "Attach an on-demand cluster profile of this duration to every "
  "flight-recorder bundle (profile_trace.json); 0 disables.  The train "
  "watchdog's bundle_profile_s knob overrides this for its own trip "
  "bundles.")

# --- Syncer ----------------------------------------------------------------
D("syncer_period_s", float, 1.0,
  "Node resource-view sampling period; views are sent to the head only "
  "when changed (reference: ray_syncer.h versioned broadcast).")

# --- Resource isolation (reference: src/ray/common/cgroup2/) ---------------
D("enable_resource_isolation", bool, False,
  "Isolate worker processes (cgroup v2 when writable, RLIMIT_AS fallback) "
  "— reference: cgroup_manager.h opt-in isolation.")
D("worker_memory_limit_bytes", int, 0,
  "Per-worker-tree memory cap (cgroup memory.max / worker RLIMIT_AS); "
  "0 = unlimited.")
D("worker_cgroup_cpu_weight", int, 0,
  "cpu.weight for the workers cgroup (cgroup tier only); 0 = default.")

# --- Memory monitor / OOM killing ------------------------------------------
# 0 disables the monitor (the reference defaults to 250ms-on; here the
# default is off so shared CI hosts under external memory pressure don't
# nondeterministically kill test workers — production nodes enable it).
D("memory_monitor_refresh_ms", int, 0,
  "Memory monitor poll period; 0 disables (reference: ray_config_def.h "
  "memory_monitor_refresh_ms).")
D("memory_usage_threshold", float, 0.95,
  "Node memory usage fraction above which a worker is OOM-killed "
  "(reference: memory_usage_threshold).")
D("memory_monitor_kill_interval_s", float, 2.0,
  "Minimum time between successive OOM kills (reference: "
  "min_memory_free_bytes backoff semantics).")
D("memory_monitor_test_fraction", float, 0.0,
  "Testing hook: fake observed memory usage fraction (>0 overrides real "
  "sampling so OOM paths are deterministically testable).")

# --- Logging ---------------------------------------------------------------
D("log_level", str, "INFO", "Runtime log level.")
D("session_dir", str, "", "Session directory (empty = /tmp/ray_tpu/session_*).")
D("redirect_worker_logs", bool, True,
  "Redirect worker stdout/stderr to per-worker session log files, tailed "
  "back to the driver by the log monitor (reference: log_monitor.py:116).")
D("log_monitor_poll_ms", int, 200, "Log monitor tail poll period.")
