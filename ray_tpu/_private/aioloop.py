"""Shared teardown for daemon-thread asyncio servers.

The serve HTTP ingress, the dashboard and the job server all run an
aiohttp app on a private event loop inside a daemon thread.  Their
teardown has two sharp edges that must be handled identically in all
three (and were once copy-pasted, drifting apart):

* the loop's *default executor* keeps its ``asyncio_N`` worker threads
  (every ``run_in_executor`` get) alive forever unless shut down WITH
  the loop — a per-server thread leak the sanitizer flags at cluster
  shutdown;
* once the loop is closed, ``call_soon_threadsafe`` raises
  ``RuntimeError`` — a second ``stop()`` (or one racing the serve
  thread's own exit) must be a no-op, not an exception that aborts the
  caller's shutdown sequence.
"""

from __future__ import annotations

from typing import Any, Optional


def shutdown_loop(loop: Any) -> None:
    """Run on the loop's own thread after ``run_until_complete``
    returns: retire the default executor, then close the loop."""
    try:
        loop.run_until_complete(loop.shutdown_default_executor())
    except Exception:
        pass
    try:
        loop.close()
    except Exception:
        pass


def stop_loop_thread(loop: Any, thread: Optional[Any],
                     join_timeout: float = 5.0) -> None:
    """Request the loop stop from any thread and join its host thread.
    Safe against an already-exited (closed) loop and double stops."""
    if loop is not None and not loop.is_closed():
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass  # loop closed between the check and the call
    if thread is not None:
        thread.join(timeout=join_timeout)
