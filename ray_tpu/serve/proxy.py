"""Per-node Serve proxies: HTTP + gRPC ingress actors.

The reference runs an HTTP/gRPC ProxyActor on every node so ingress
scales with the cluster and survives any single serving process
(reference: python/ray/serve/_private/proxy.py:601 HTTPProxy, :1084
gRPCProxy, :1633 per-node actor startup).  Here ``start_node_proxies``
places one ProxyActor per alive node (node-affinity scheduling); each
serves:

- HTTP: the shared ingress aiohttp app (api.build_ingress_app) — POST
  /{deployment} with a JSON body, chunked ndjson when streaming.
- gRPC: a proto-free generic service: call method
  ``/ray_tpu.serve/<deployment>`` with a JSON-encoded request message;
  the reply is JSON bytes.  A server-streaming variant
  ``/ray_tpu.serve.stream/<deployment>`` yields one JSON message per
  generator item.  (Schema-free by design: the pickle-native framework
  has no proto layer to hang typed stubs from; the reference's typed
  gRPC ingress is driven by user-supplied protos.)

Requests route through the same pow-2 deployment routers every process
uses, riding the direct worker->worker actor channels to replicas.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

import ray_tpu

PROXY_NAME_PREFIX = "SERVE_PROXY"
NAMESPACE = "serve"


class _ProxyImpl:
    """Runs inside the proxy actor's worker process."""

    def __init__(self, http_port: int, grpc_port: int):
        from . import api as serve_api

        self._http = serve_api._HttpServer(http_port, host="0.0.0.0") \
            if http_port >= 0 else None
        self.http_port = self._http.port if self._http else None
        self.grpc_port: Optional[int] = None
        self._grpc = None
        if grpc_port >= 0:
            self._grpc = self._start_grpc(grpc_port)

    def _start_grpc(self, port: int):
        import grpc

        from . import api as serve_api

        class GenericIngress(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method  # /<service>/<method>
                parts = method.strip("/").split("/", 1)
                if len(parts) != 2:
                    return None
                service_name, deployment = parts
                if not service_name.startswith("ray_tpu.serve"):
                    # Typed proto service registered via
                    # serve.add_grpc_service (grpc_ingress.py): real
                    # FromString/SerializeToString handlers — any stock
                    # gRPC client with the same proto works.
                    from .grpc_ingress import make_typed_handlers
                    try:
                        typed = make_typed_handlers(service_name,
                                                    deployment)
                    except Exception:  # registry/import error -> 404
                        typed = None
                    if typed is None:
                        return None
                    handler, req_des, resp_ser, t_stream = typed

                    def typed_unary(request, ctx, _h=handler):
                        try:
                            return _h(request, ctx)
                        except Exception as e:  # noqa: BLE001
                            ctx.set_code(grpc.StatusCode.INTERNAL)
                            ctx.set_details(repr(e))
                            return None

                    def typed_stream(request, ctx, _h=handler):
                        try:
                            yield from _h(request, ctx)
                        except Exception as e:  # noqa: BLE001
                            ctx.set_code(grpc.StatusCode.INTERNAL)
                            ctx.set_details(repr(e))

                    if t_stream:
                        return grpc.unary_stream_rpc_method_handler(
                            typed_stream, request_deserializer=req_des,
                            response_serializer=resp_ser)
                    return grpc.unary_unary_rpc_method_handler(
                        typed_unary, request_deserializer=req_des,
                        response_serializer=resp_ser)
                streaming = service_name.endswith(".stream")

                def unary(request: bytes, ctx):
                    ref = None
                    try:
                        body = json.loads(request or b"{}")
                        h = serve_api.get_deployment_handle(deployment)
                        # remote() counts its own errors (no live
                        # replicas) — only count past that point.
                        ref = h.remote(body)
                        result = ray_tpu.get(ref, timeout=300)
                        return json.dumps({"result": result}).encode()
                    except Exception as e:  # noqa: BLE001
                        if ref is not None:
                            from ..util import telemetry
                            telemetry.inc(
                                "ray_tpu_serve_request_errors_total",
                                tags={"deployment": deployment})
                        ctx.set_code(grpc.StatusCode.INTERNAL)
                        ctx.set_details(repr(e))
                        return b"{}"

                def stream(request: bytes, ctx):
                    try:
                        body = json.loads(request or b"{}")
                        h = serve_api.get_deployment_handle(
                            deployment).options(stream=True)
                        for item_ref in h.remote(body):
                            item = ray_tpu.get(item_ref, timeout=300)
                            yield json.dumps({"result": item}).encode()
                    except Exception as e:  # noqa: BLE001
                        ctx.set_code(grpc.StatusCode.INTERNAL)
                        ctx.set_details(repr(e))

                if streaming:
                    return grpc.stream_stream_rpc_method_handler(
                        lambda req_iter, ctx: stream(next(req_iter), ctx))
                return grpc.unary_unary_rpc_method_handler(unary)

        from concurrent.futures import ThreadPoolExecutor
        server = grpc.server(ThreadPoolExecutor(max_workers=8))
        server.add_generic_rpc_handlers((GenericIngress(),))
        bound = server.add_insecure_port(f"0.0.0.0:{port}")
        if bound == 0:
            raise RuntimeError(f"grpc ingress failed to bind port {port}")
        self.grpc_port = bound
        server.start()
        return server

    def addresses(self) -> Dict[str, Optional[int]]:
        return {"http_port": self.http_port, "grpc_port": self.grpc_port}

    def shutdown(self) -> None:
        if self._http is not None:
            self._http.stop()
        if self._grpc is not None:
            self._grpc.stop(grace=1.0)


@ray_tpu.remote
class ProxyActor:
    """One ingress endpoint, pinned to its node (reference:
    proxy.py:1633 — a proxy actor per node, named per node id)."""

    def __init__(self, http_port: int = 0, grpc_port: int = 0):
        self._impl = _ProxyImpl(http_port, grpc_port)

    def addresses(self) -> Dict[str, Optional[int]]:
        return self._impl.addresses()

    def ping(self) -> str:
        return "ok"

    def shutdown(self) -> None:
        self._impl.shutdown()


def start_node_proxies(http_port: int = 0, grpc_port: int = 0,
                       ) -> Dict[str, Dict[str, Optional[int]]]:
    """Start (idempotently) one ProxyActor per alive node; returns
    {node_id_hex: {"http_port": ..., "grpc_port": ...}}.  Ports of 0 bind
    ephemerally (per node); -1 disables that protocol."""
    from .._private.api import _control
    from ray_tpu import NodeAffinitySchedulingStrategy

    out: Dict[str, Dict[str, Optional[int]]] = {}
    for node in _control("nodes"):
        if not node.get("alive", True):
            continue
        hexid = node["node_id"] if isinstance(node["node_id"], str) \
            else node["node_id"].hex()
        name = f"{PROXY_NAME_PREFIX}:{hexid}"
        existing = _control("get_named_actor", name, NAMESPACE)
        if existing is not None:
            from .._private.api import ActorHandle
            from .._private.ids import ActorID
            h = ActorHandle(ActorID(existing[0]), existing[2])
        else:
            h = ProxyActor.options(
                name=name, namespace=NAMESPACE,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    _node_id_from_hex(hexid), soft=False),
            ).remote(http_port, grpc_port)
        out[hexid] = ray_tpu.get(h.addresses.remote(), timeout=120)
    return out


def _node_id_from_hex(hexid: str):
    from .._private.ids import NodeID
    return NodeID(bytes.fromhex(hexid))


def stop_node_proxies() -> None:
    from .._private.api import _control
    for node in _control("nodes"):
        hexid = node["node_id"] if isinstance(node["node_id"], str) \
            else node["node_id"].hex()
        existing = _control("get_named_actor",
                            f"{PROXY_NAME_PREFIX}:{hexid}", NAMESPACE)
        if existing is None:
            continue
        from .._private.api import ActorHandle
        from .._private.ids import ActorID
        h = ActorHandle(ActorID(existing[0]), existing[2])
        try:
            ray_tpu.get(h.shutdown.remote(), timeout=30)
            ray_tpu.kill(h)
        except Exception:
            pass
