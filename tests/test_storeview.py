"""Data-plane telescope: object-lifecycle ring, unified store stats,
enriched ObjectStoreFullError, spill-file GC, the memory-summary /
explain-object control verbs, cross-node transfer accounting, and the
tier-1 smoke of ``bench.py --spec dataplane --fast``.

Reference analogs: ``ray memory`` (python/ray/_private/state.py memory
summary) and the object-transfer accounting in
src/ray/object_manager/{pull_manager,push_manager}.h — but here the
lifecycle *history* is queryable, not just the instantaneous state.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from ray_tpu._private import object_store as store_mod
from ray_tpu._private.ids import JobID, ObjectID, TaskID
from ray_tpu._private.object_store import (ObjectStoreFullError,
                                           SharedMemoryStore,
                                           sweep_orphan_spills)
from ray_tpu.storeview import events as sv


def _oid(i: int) -> ObjectID:
    return ObjectID.of(TaskID.for_driver(JobID.next()), i)


def _wait_for(predicate, timeout_s: float = 30.0, interval_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(interval_s)
    raise AssertionError("condition not met within timeout")


# ---------------------------------------------------------------------------
# StoreEventRing unit tests
# ---------------------------------------------------------------------------


class TestStoreEventRing:
    def test_lifecycle_fold_and_explain(self):
        ring = sv.StoreEventRing(capacity=256)
        key = _oid(1).binary()
        ring.push(sv.E_CREATE, key, 1000)
        ring.push(sv.E_SEAL, key)
        ring.push(sv.E_GET, key)
        ring.push(sv.E_GET, key)
        out = ring.explain(key.hex())
        assert out["status"] == "ok"
        assert out["state"] == "sealed"
        assert out["nbytes"] == 1000
        assert out["reads"] == 2
        assert [e["kind"] for e in out["events"]] == \
            ["create", "seal", "get", "get"]
        assert out["age_s"] >= 0.0
        ring.push(sv.E_DELETE, key)
        assert ring.explain(key.hex())["state"] == "deleted"

    def test_explain_unknown_and_ambiguous_prefix(self):
        ring = sv.StoreEventRing(capacity=256)
        a, b = _oid(1).binary(), _oid(2).binary()
        assert ring.explain("feedbeef")["status"] == "unknown"
        ring.push(sv.E_CREATE, a, 10)
        ring.push(sv.E_CREATE, b, 10)
        # Both ids share the leading task-id bytes? No — different jobs.
        # Force ambiguity with the empty prefix (matches everything).
        amb = ring.explain("")
        assert amb["status"] == "ambiguous"
        assert len(amb["matches"]) == 2
        # An exact full id resolves.
        assert ring.explain(a.hex())["status"] == "ok"

    def test_bounded_ring_counts_drops(self):
        ring = sv.StoreEventRing(capacity=64)
        key = _oid(1).binary()
        for _ in range(300):
            ring.push(sv.E_GET, key)
        st = ring.stats()
        assert st["counts"]["get"] == 300
        assert st["total"] == 300
        assert st["size"] <= st["capacity"] == 64
        assert st["num_dropped"] > 0
        assert st["tracked"] == 1

    def test_pin_accounting_and_top_pinned(self):
        ring = sv.StoreEventRing(capacity=256)
        small, big = _oid(1).binary(), _oid(2).binary()
        ring.push(sv.E_CREATE, small, 100)
        ring.push(sv.E_PIN, small, detail="worker_a")
        ring.push(sv.E_CREATE, big, 9000)
        ring.push(sv.E_PIN, big, detail="ckpt_pin")
        ring.push(sv.E_PIN, big, detail="worker_b")
        top = ring.top_pinned(2)
        assert top[0]["object_id"] == big.hex()
        assert top[0]["pins"] == 2
        assert set(top[0]["pinners"]) == {"ckpt_pin", "worker_b"}
        assert ring.pinners_of(small) == ["worker_a"]
        # Unpinning the last pin clears the pinner list.
        ring.push(sv.E_UNPIN, small, detail="worker_a")
        assert ring.pinners_of(small) == []
        assert ring.top_pinned(5)[0]["object_id"] == big.hex()
        assert len(ring.top_pinned(5)) == 1

    def test_leak_candidates_sealed_never_read(self):
        ring = sv.StoreEventRing(capacity=256)
        leaked, read_obj = _oid(1).binary(), _oid(2).binary()
        for key in (leaked, read_obj):
            ring.push(sv.E_CREATE, key, 500)
            ring.push(sv.E_SEAL, key)
        ring.push(sv.E_GET, read_obj)
        time.sleep(0.05)
        leaks = ring.leak_candidates(ttl_s=0.01)
        assert [r["object_id"] for r in leaks] == [leaked.hex()]
        assert leaks[0]["reason"] == "sealed_never_read"
        # A later read clears the candidate.
        ring.push(sv.E_GET, leaked)
        assert ring.leak_candidates(ttl_s=0.01) == []

    def test_leak_candidates_dead_incarnation(self):
        ring = sv.StoreEventRing(capacity=256)
        dead, label = _oid(1).binary(), _oid(2).binary()
        dead_token = "ab" * 14  # 28 hex chars: a worker-id incarnation
        for key in (dead, label):
            ring.push(sv.E_CREATE, key, 500)
            ring.push(sv.E_SEAL, key)
            ring.push(sv.E_GET, key)  # reads exempt the TTL rule
        ring.push(sv.E_PIN, dead, detail=dead_token)
        # Descriptive labels are not incarnations: never counted dead.
        ring.push(sv.E_PIN, label, detail="ckpt_pin")
        leaks = ring.leak_candidates(ttl_s=3600.0, live_tokens={"cafe" * 7})
        assert [r["object_id"] for r in leaks] == [dead.hex()]
        assert leaks[0]["reason"] == "pinned_by_dead_incarnation"
        # The same pin is healthy while its incarnation is alive.
        assert ring.leak_candidates(ttl_s=3600.0,
                                    live_tokens={dead_token}) == []

    def test_enable_switch_defaults_on(self):
        assert sv.enabled()
        sv.set_enabled(False)
        try:
            assert not sv.enabled()
        finally:
            sv.set_enabled(True)
        assert sv.enabled()


# ---------------------------------------------------------------------------
# Store-level behaviors: unified stats, enriched full error, spill events
# ---------------------------------------------------------------------------


class TestUnifiedStoreStats:
    EXPECTED = {"num_objects", "used_bytes", "capacity_bytes",
                "pinned_bytes", "spilled_bytes", "num_spilled",
                "num_restored", "num_evictions", "num_in_memory",
                "num_pinned", "native"}

    def test_python_store_keys(self):
        s = SharedMemoryStore(capacity_bytes=1 << 20)
        try:
            assert set(s.stats()) == self.EXPECTED
            assert s.stats()["native"] == 0
        finally:
            s.shutdown()

    def test_native_store_keys_match(self, tmp_path):
        from ray_tpu._native import load_store_library
        from ray_tpu._private.object_store import NativeArenaStore
        if load_store_library() is None:
            pytest.skip("no C++ toolchain")
        s = NativeArenaStore(capacity_bytes=1 << 20,
                             spill_dir=str(tmp_path / "spill"))
        try:
            assert set(s.stats()) == self.EXPECTED
            assert s.stats()["native"] == 1
        finally:
            s.shutdown()


class TestStoreFullErrorEnrichment:
    def test_message_names_top_pinned_and_pinners(self):
        s = SharedMemoryStore(capacity_bytes=1 << 20)
        try:
            hog = _oid(1)
            view = s.create(hog, 700_000)
            view.release()
            s.seal(hog)
            s.pin(hog, pinner="ckpt_pin")
            with pytest.raises(ObjectStoreFullError) as ei:
                s.create(_oid(2), 700_000)
            msg = str(ei.value)
            assert "top pinned" in msg
            assert hog.hex()[:12] in msg
            assert "ckpt_pin" in msg
            s.unpin(hog, pinner="ckpt_pin")
        finally:
            s.shutdown()


class TestSpillLifecycleEvents:
    def test_spill_then_restore_records_ring_evidence(self, tmp_path):
        s = SharedMemoryStore(capacity_bytes=1 << 20,
                              spill_dir=str(tmp_path / "spill"))
        try:
            oids = [_oid(i) for i in range(3)]
            for oid in oids:  # 3 x 500KB > 1MB: first object spills
                view = s.create(oid, 500_000)
                view[:] = b"\xaa" * 500_000
                view.release()
                s.seal(oid)
            stats = s.stats()
            assert stats["num_spilled"] >= 1
            assert stats["spilled_bytes"] >= 500_000
            out = s.view.explain(oids[0].hex())
            assert out["state"] == "spilled"
            assert out["spills"] == 1 and out["spilled"]
            # Reading the spilled object restores it; both halves of the
            # round trip land in the ring, and counts agree with stats.
            view, _keep = s.get_buffer(oids[0])
            assert bytes(view[:4]) == b"\xaa" * 4
            view.release()
            out = s.view.explain(oids[0].hex())
            assert out["restores"] == 1 and not out["spilled"]
            kinds = [e["kind"] for e in out["events"]]
            assert kinds.index("spill") < kinds.index("restore")
            rc = s.view.stats()["counts"]
            assert rc["spill"] == s.stats()["num_spilled"]
            assert rc["restore"] == s.stats()["num_restored"]
        finally:
            s.shutdown()


class TestSameHostPullDedupe:
    def test_put_raw_reuses_producer_segment(self, tmp_path):
        """shm names are host-global (`rt_<oid>`): when the producer of a
        pulled object lives on the same host, the puller's put_raw must
        hand back a descriptor onto the live segment instead of crashing
        on the name collision (FileExistsError)."""
        from ray_tpu._private.object_store import RemoteObjectReader

        producer = SharedMemoryStore(capacity_bytes=1 << 20,
                                     spill_dir=str(tmp_path / "p"))
        puller = SharedMemoryStore(capacity_bytes=1 << 20,
                                   spill_dir=str(tmp_path / "q"))
        try:
            oid = _oid(1)
            producer.put(oid, {"blob": b"\xbc" * 4096})
            payload = producer.read_raw_by_key(oid.binary())
            assert payload is not None

            desc = puller.put_raw(oid, payload)
            assert desc is not None and desc[0] == "shm"
            assert desc[2] == len(payload)
            # The descriptor resolves to the producer's live segment.
            got, shm = RemoteObjectReader.read(desc[1], desc[2])
            try:
                assert got["blob"] == b"\xbc" * 4096
                assert producer.contains(oid)
                # No duplicate entry was cached in the pulling store.
                assert not puller.contains(oid)
            finally:
                shm.close()
        finally:
            producer.shutdown()
            puller.shutdown()


class TestSpillFileGC:
    def test_sweep_reclaims_only_dead_pid_dirs(self, tmp_path):
        root = str(tmp_path / "spill_root")
        # A pid that existed and is now dead (spawn + reap).
        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.wait()
        dead_pid = proc.pid
        for name, nbytes in ((str(dead_pid), 1000),
                             (f"arena_{dead_pid}", 2000),
                             (str(os.getpid()), 4000),   # live: ours
                             ("not_a_pid", 8000)):       # unrelated
            d = os.path.join(root, name)
            os.makedirs(d)
            with open(os.path.join(d, "obj"), "wb") as f:
                f.write(b"\0" * nbytes)
        reclaimed = sweep_orphan_spills(root=root)
        assert reclaimed == 3000
        assert not os.path.exists(os.path.join(root, str(dead_pid)))
        assert not os.path.exists(os.path.join(root, f"arena_{dead_pid}"))
        assert os.path.exists(os.path.join(root, str(os.getpid())))
        assert os.path.exists(os.path.join(root, "not_a_pid"))
        # Idempotent: nothing left to reclaim.
        assert sweep_orphan_spills(root=root) == 0

    def test_shutdown_sweeps_own_default_spill_dir(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setattr(store_mod, "SPILL_ROOT",
                            str(tmp_path / "spill_root"))
        own = os.path.join(store_mod.SPILL_ROOT, str(os.getpid()))
        os.makedirs(own)
        with open(os.path.join(own, "orphan"), "wb") as f:
            f.write(b"\0" * 512)
        s = SharedMemoryStore(capacity_bytes=1 << 20)  # default spill dir
        s.shutdown()
        assert not os.path.exists(own)


# ---------------------------------------------------------------------------
# Live runtime: memory summary, explain_object, leak candidates, gauges
# ---------------------------------------------------------------------------


@pytest.fixture()
def small_store_runtime(monkeypatch):
    """Isolated runtime whose head store is a 4 MiB *Python* store, so
    spill pressure is cheap to provoke and every lifecycle event (spill
    decisions included) lands in the ring."""
    monkeypatch.setenv("RAY_TPU_OBJECT_STORE_MEMORY", str(4 << 20))
    monkeypatch.setenv("RAY_TPU_USE_NATIVE_STORE", "0")
    import ray_tpu
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    yield
    ray_tpu.shutdown()


class TestMemoryIntrospectionLive:
    def test_summary_explain_spill_pin_and_events(self, small_store_runtime):
        import ray_tpu
        from ray_tpu._private.api import _control
        from ray_tpu.util import state

        a = ray_tpu.put(np.zeros(1_500_000, dtype=np.uint8))
        b = ray_tpu.put(np.ones(1_500_000, dtype=np.uint8))
        c = ray_tpu.put(np.full(1_500_000, 2, dtype=np.uint8))

        # 4.5MB into a 4MiB store: the LRU head (a) spilled.
        out = state.explain_object(a.hex())
        assert out["status"] == "ok"
        assert out["directory"]["state"] == "shm"
        assert out["directory"]["error"] is False
        assert out["local"]["spills"] >= 1 and out["local"]["spilled"]

        summary = state.memory_summary(top_n=5)
        assert summary["totals"]["capacity_bytes"] == 4 << 20
        assert summary["totals"]["num_spilled"] >= 1
        assert summary["totals"]["spilled_bytes"] >= 1_500_000
        assert summary["num_directory_objects"] >= 3
        assert len(summary["nodes"]) >= 1
        top_ids = [o["object_id"] for o in summary["top_objects"]]
        assert b.hex() in top_ids and c.hex() in top_ids

        # Reading the spilled object restores it (visible in explain).
        arr = ray_tpu.get(a)
        assert arr.nbytes == 1_500_000
        out = state.explain_object(a.hex())
        assert out["local"]["restores"] >= 1
        assert not out["local"]["spilled"]

        # Pin via the checkpoint pin verb: explain names the pinner.
        assert _control("pin_object", a.binary()) is True
        out = state.explain_object(a.hex())
        assert out["local"]["pins"] >= 1
        assert "ckpt_pin" in out["local"]["pinners"]
        assert _control("unpin_object", a.binary()) is True

        # The raw event tail carries the whole story, filterable by id.
        ev = state.store_events(object_id=a.hex(), limit=100)
        kinds = [e["kind"] for e in ev["events"]]
        for expected in ("create", "seal", "spill", "restore", "pin",
                         "unpin"):
            assert expected in kinds, (expected, kinds)
        assert ev["stats"]["counts"]["spill"] >= 1

        # Prefix queries resolve; garbage ids answer unknown, not raise.
        assert state.explain_object(a.hex()[:16])["status"] in \
            ("ok", "ambiguous")
        assert state.explain_object("feedbeefcafe")["status"] == "unknown"
        del b, c

    def test_leak_candidate_surfaces_in_summary(self, small_store_runtime,
                                                monkeypatch):
        import ray_tpu
        from ray_tpu.util import state

        monkeypatch.setattr(sv, "LEAK_TTL_S", 0.05)
        leaked = ray_tpu.put(np.zeros(300_000, dtype=np.uint8))
        time.sleep(0.2)

        def leaked_reported():
            leaks = state.memory_summary()["leak_candidates"]
            return [r for r in leaks if r["object_id"] == leaked.hex()]

        rec = _wait_for(leaked_reported, timeout_s=10.0)[0]
        assert rec["reason"] == "sealed_never_read"
        assert rec["nbytes"] >= 300_000  # serialized payload: data + meta
        assert "node_id" in rec
        # Reading it clears the candidate.
        ray_tpu.get(leaked)
        _wait_for(lambda: not leaked_reported(), timeout_s=10.0)

    def test_store_gauges_queryable_via_metrics_path(self,
                                                     small_store_runtime):
        import ray_tpu
        from ray_tpu._private import runtime as rt_mod
        from ray_tpu.util import state

        ref = ray_tpu.put(np.zeros(500_000, dtype=np.uint8))
        summary = state.memory_summary()  # forces the gauge publisher
        assert summary["totals"]["used_bytes"] >= 500_000
        rt_mod.driver_runtime().metricsview.refresh(force=True)
        # Tag-filter to this runtime's head node: the process-global
        # registry keeps node-tagged gauge series from earlier inits in
        # the same pytest process, and an unfiltered multi-series match
        # would fold those stale nodes in.
        head_hex = rt_mod.driver_runtime().node_id.hex()
        q = state.metrics_query("ray_tpu_store_used_bytes",
                                window_s=300.0, agg="last",
                                tags={"node": head_hex})
        assert q["value"] is not None and q["value"] >= 500_000
        q = state.metrics_query("ray_tpu_store_ops_total",
                                window_s=300.0, agg="last",
                                tags={"op": "create"})
        assert q["value"] is not None and q["value"] >= 1
        del ref


# ---------------------------------------------------------------------------
# Cross-node: remote attribution + transfer accounting
# ---------------------------------------------------------------------------


class TestCrossNodeTransfer:
    def test_remote_object_attributed_and_pull_accounted(self):
        import ray_tpu
        from ray_tpu._private import runtime as rt_mod
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.util import state

        with Cluster(head_num_cpus=0) as cluster:
            cluster.add_node(num_cpus=2)
            cluster.add_node(num_cpus=2)
            rt = cluster.runtime
            head_hex = rt.node_id.hex()

            @ray_tpu.remote(num_cpus=1)
            def produce():
                return np.full(300_000, 7, dtype=np.uint8)

            ref = produce.remote()

            # The directory attributes the result to its OWNER node (the
            # worker node that produced it), not the head.
            def owned_remotely():
                recs = [r for r in state.list_objects()
                        if r["object_id"] == ref.hex()]
                if recs and recs[0].get("node_id") not in (None, head_hex):
                    return recs[0]
                return None

            rec = _wait_for(owned_remotely)
            owner_hex = rec["node_id"]
            assert rec["size_bytes"] > 100 * 1024  # too big to inline

            out = state.explain_object(ref.hex())
            assert out["status"] == "ok"
            assert out["directory"]["node_id"] == owner_hex

            # Driver get = cross-node pull through the data plane: the
            # head ring records it, with latency + the peer node.
            arr = ray_tpu.get(ref)
            assert arr[0] == 7 and arr.nbytes == 300_000
            out = state.explain_object(ref.hex())
            assert out["local"]["pulls"] >= 1
            assert out["local"]["pull_bytes"] >= 300_000
            assert out["local"]["pull_avg_ms"] >= 0.0
            assert out["local"]["last_peer"] == owner_hex[:16]
            ev = state.store_events(object_id=ref.hex())
            assert "pull" in [e["kind"] for e in ev["events"]]

            # The memory summary eventually shows the owner node's store
            # occupancy (synced view) alongside the head's.
            def summary_covers_owner():
                nodes = state.memory_summary()["nodes"]
                sub = nodes.get(owner_hex)
                return sub if sub and sub.get("num_objects", 0) >= 1 \
                    else None
            _wait_for(summary_covers_owner)

            # And the transfer series are queryable through the
            # production metrics path on the head.
            rt.metricsview.refresh(force=True)
            q = state.metrics_query("ray_tpu_store_transfer_bytes_total",
                                    window_s=300.0, agg="last",
                                    tags={"direction": "pull"})
            assert q["value"] is not None and q["value"] >= 300_000
            qh = state.metrics_query("ray_tpu_store_transfer_seconds",
                                     window_s=300.0, agg="last")
            assert qh["value"] is not None


# ---------------------------------------------------------------------------
# Bench: checked-in baseline gate + tier-1 fast smoke
# ---------------------------------------------------------------------------


class TestDataplaneBenchGate:
    """The checked-in BENCH_dataplane.json is the data-plane throughput/
    overhead baseline the next store PR measures against."""

    def _load(self):
        path = os.path.join(REPO_ROOT, "BENCH_dataplane.json")
        assert os.path.exists(path), "BENCH_dataplane.json baseline missing"
        with open(path) as f:
            return path, json.load(f)

    def test_checked_in_baseline_holds_gates(self):
        _path, doc = self._load()
        assert doc["pass"] is True
        tr = doc["tracing"]
        assert tr["within_budget"]
        assert tr["overhead_pct"] < 2.0 or tr["amortized_pct"] < 2.0
        assert tr["per_event_ns"] > 0 and tr["events_per_op"] == 4
        assert doc["spill"]["ring_complete"]
        assert doc["spill"]["num_spilled"] >= 1
        assert doc["transfer"]["series_queryable"]
        assert doc["transfer"]["ring_pull_events"] == \
            doc["transfer"]["objects"]
        assert doc["transfer"]["pull_mb_per_s"] > 0
        for size in ("4096", "65536", "1048576"):
            assert doc["putget"][size]["mb_per_s"] > 0, size

    def test_compare_gate_covers_dataplane_metrics(self):
        import bench
        path, doc = self._load()
        out = bench.compare_bench(path, path, threshold=0.10)
        assert not out["regressions"]
        flat = bench._flatten_bench(doc)
        gated = [p for p in flat if bench._metric_direction(p) is not None]
        assert any("pull_mb_per_s" in p for p in gated)
        assert any("ops_per_s" in p for p in gated)
        assert any("overhead_pct" in p for p in gated)
        assert any(p.endswith("pass") for p in gated)


class TestDataplaneBenchSmoke:
    def test_fast_bench_end_to_end(self, tmp_path):
        """`bench.py --spec dataplane --fast` wired into tier-1 as a
        smoke, in a subprocess with a hard wall bound: put/get
        throughput, the tracing-overhead gate, the spill-pressure phase
        with ring-completeness evidence, and the loopback transfer phase
        asserting the transfer series are queryable."""
        out = str(tmp_path / "BENCH_dataplane.json")
        code = (
            "import bench, json\n"
            "try:\n"
            f"    bench.bench_dataplane(fast=True, out_path={out!r})\n"
            "except SystemExit:\n"
            "    pass\n"
            "print('BENCH_DONE')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="", XLA_FLAGS="")

        def run_once():
            proc = subprocess.run(
                [sys.executable, "-u", "-c", code], cwd=REPO_ROOT,
                env=env, capture_output=True, text=True, timeout=420)
            assert proc.returncode == 0 and "BENCH_DONE" in proc.stdout, \
                f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n" \
                f"{proc.stderr[-4000:]}"
            with open(out) as f:
                return json.load(f)

        doc = run_once()
        if not doc["pass"] and not doc["tracing"]["within_budget"] and \
                doc["spill"]["ring_complete"] and \
                doc["transfer"]["series_queryable"]:
            # The paired off/on loop has residual shm-syscall jitter on
            # a loaded CI box; the deterministic amortized bound usually
            # arbitrates, but one retry bounds the tail without
            # weakening the strict gate on the checked-in FULL baseline.
            doc = run_once()
        assert doc["pass"] is True, doc
        assert doc["spill"]["ring_complete"]
        assert doc["transfer"]["ring_pull_events"] == \
            doc["transfer"]["objects"]
        assert doc["transfer"]["series_queryable"]
