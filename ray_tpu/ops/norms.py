"""Normalization ops."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm in fp32 accumulation, cast back to input dtype.

    Elementwise chain (square, mean, rsqrt, mul) fuses into neighboring
    matmuls under XLA; no pallas needed at current sizes.
    """
    import jax.lax as lax
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
