"""ray_tpu.train — SPMD training orchestration (Ray Train v2 equivalent)."""

from ._checkpoint import (Checkpoint, CheckpointManager, load_pytree,
                          save_pytree)
from ._context import (TrainContext, get_context, load_checkpoint, report,
                       save_checkpoint)
from .controller import CrashLoopError
from .trainer import (CheckpointConfig, FailureConfig, JaxTrainer, Result,
                      RunConfig, ScalingConfig)
from .watchdog import TrainWatchdog, WatchdogConfig

__all__ = [
    "JaxTrainer", "ScalingConfig", "RunConfig", "FailureConfig",
    "CheckpointConfig", "Result", "Checkpoint", "CheckpointManager",
    "get_context", "report", "TrainContext", "save_pytree", "load_pytree",
    "save_checkpoint", "load_checkpoint", "CrashLoopError",
    "WatchdogConfig", "TrainWatchdog",
]
